"""paddle.nn.quant parity (reference: python/paddle/nn/quant/quant_layers.py).

The reference's FakeQuant* layers simulate int8 quantization during QAT
with straight-through gradients; here they are thin Layer wrappers over
paddle_tpu.quantization's STE fake_quant + observers, which the
quantization module's ImperativePTQ/ImperativeQuantAware already insert.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.quantization import fake_quant

__all__ = [
    "FakeQuantAbsMax", "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax", "FakeQuantMAOutputScaleLayer",
    "QuantStub", "quant_dequant",
]


def quant_dequant(x, scale, bits=8):
    """Round-trip through the int grid with STE gradients."""
    return fake_quant(x, scale, bits)


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax fake quantization (reference quant_layers.py
    FakeQuantAbsMax)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 quant_on_weight=False):
        super().__init__()
        self.bits = quant_bits

    def forward(self, x):
        scale = float(np.abs(np.asarray(x._value)).max()) or 1.0
        qmax = 2 ** (self.bits - 1) - 1
        return fake_quant(x, scale / qmax, self.bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel absmax fake quantization."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", quant_on_weight=True):
        super().__init__()
        self.bits = quant_bits
        self.axis = quant_axis

    def forward(self, x):
        v = np.asarray(x._value)
        axes = tuple(i for i in range(v.ndim) if i != self.axis)
        amax = np.abs(v).max(axis=axes, keepdims=True)
        amax = np.where(amax == 0, 1.0, amax)
        qmax = 2 ** (self.bits - 1) - 1
        shape = [1] * v.ndim
        shape[self.axis] = -1
        return fake_quant(x, (amax / qmax).reshape(shape), self.bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quantization with an EMA absmax scale (reference
    FakeQuantMovingAverageAbsMax): the running scale is a persistable
    state tensor so QAT checkpoints carry it."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32"):
        super().__init__()
        self.rate = moving_rate
        self.bits = quant_bits
        self.scale = self.create_parameter([1])
        self.scale._set_value(jnp.ones((1,), jnp.float32))
        self.scale.stop_gradient = True

    def forward(self, x):
        if self.training:
            cur = float(np.abs(np.asarray(x._value)).max()) or 1e-7
            new = self.rate * float(self.scale._value[0]) \
                + (1 - self.rate) * cur
            self.scale._set_value(jnp.asarray([new], jnp.float32))
        qmax = 2 ** (self.bits - 1) - 1
        return fake_quant(x, float(self.scale._value[0]) / qmax, self.bits)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer and fake-quantize its OUTPUT with a moving-average
    scale (reference FakeQuantMAOutputScaleLayer)."""

    def __init__(self, layer, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self._layer = layer
        self._fq = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        return self._fq(self._layer(*args, **kwargs))


class QuantStub(Layer):
    """Input quant marker (reference nn/quant/stub.py): observes and
    fake-quantizes the network input."""

    def __init__(self, observer=None):
        super().__init__()
        self._fq = FakeQuantMovingAverageAbsMax()

    def forward(self, x):
        return self._fq(x)
