"""paddle.nn.quant parity (reference: python/paddle/nn/quant/quant_layers.py).

The reference's FakeQuant* layers simulate int8 quantization during QAT
with straight-through gradients; here they are thin Layer wrappers over
paddle_tpu.quantization's STE fake_quant + observers, which the
quantization module's ImperativePTQ/ImperativeQuantAware already insert.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.quantization import fake_quant

__all__ = [
    "FakeQuantAbsMax", "FakeQuantChannelWiseAbsMax",
    "FakeQuantMovingAverageAbsMax", "FakeQuantMAOutputScaleLayer",
    "FakeQuantWeightLSQPlus", "FakeQuantActLSQPlus", "LsqFunc",
    "LsqPlusActFunc", "MovingAverageAbsMaxScale", "MAOutputScaleLayer",
    "QuantizedLinear", "QuantizedConv2D",
    "QuantizedColumnParallelLinear", "QuantizedRowParallelLinear",
    "QuantStub", "quant_dequant",
]


def quant_dequant(x, scale, bits=8):
    """Round-trip through the int grid with STE gradients."""
    return fake_quant(x, scale, bits)


class FakeQuantAbsMax(Layer):
    """Per-tensor absmax fake quantization (reference quant_layers.py
    FakeQuantAbsMax)."""

    def __init__(self, name=None, quant_bits=8, dtype="float32",
                 quant_on_weight=False):
        super().__init__()
        self.bits = quant_bits

    def forward(self, x):
        scale = float(np.abs(np.asarray(x._value)).max()) or 1.0
        qmax = 2 ** (self.bits - 1) - 1
        return fake_quant(x, scale / qmax, self.bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-output-channel absmax fake quantization."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype="float32", quant_on_weight=True):
        super().__init__()
        self.bits = quant_bits
        self.axis = quant_axis

    def forward(self, x):
        v = np.asarray(x._value)
        axes = tuple(i for i in range(v.ndim) if i != self.axis)
        amax = np.abs(v).max(axis=axes, keepdims=True)
        amax = np.where(amax == 0, 1.0, amax)
        qmax = 2 ** (self.bits - 1) - 1
        shape = [1] * v.ndim
        shape[self.axis] = -1
        return fake_quant(x, (amax / qmax).reshape(shape), self.bits)


class FakeQuantMovingAverageAbsMax(Layer):
    """Activation fake quantization with an EMA absmax scale (reference
    FakeQuantMovingAverageAbsMax): the running scale is a persistable
    state tensor so QAT checkpoints carry it.  With observe_only the
    layer tracks the scale but passes the value through unquantized
    (the MovingAverageAbsMaxScale behavior)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32", observe_only=False):
        super().__init__()
        self.rate = moving_rate
        self.bits = quant_bits
        self.observe_only = observe_only
        self.scale = self.create_parameter([1])
        self.scale._set_value(jnp.ones((1,), jnp.float32))
        self.scale.stop_gradient = True

    def forward(self, x):
        if self.training:
            cur = float(np.abs(np.asarray(x._value)).max()) or 1e-7
            new = self.rate * float(self.scale._value[0]) \
                + (1 - self.rate) * cur
            self.scale._set_value(jnp.asarray([new], jnp.float32))
        if self.observe_only:
            return x
        qmax = 2 ** (self.bits - 1) - 1
        return fake_quant(x, float(self.scale._value[0]) / qmax, self.bits)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer and fake-quantize its OUTPUT with a moving-average
    scale (reference FakeQuantMAOutputScaleLayer)."""

    def __init__(self, layer, moving_rate=0.9, name=None, dtype="float32"):
        super().__init__()
        self._layer = layer
        self._fq = FakeQuantMovingAverageAbsMax(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        return self._fq(self._layer(*args, **kwargs))


class QuantStub(Layer):
    """Input quant marker (reference nn/quant/stub.py): observes and
    fake-quantizes the network input."""

    def __init__(self, observer=None):
        super().__init__()
        self._fq = FakeQuantMovingAverageAbsMax()

    def forward(self, x):
        return self._fq(x)


# ------------------------------------------------------- LSQ(+) quantizers
def _lsq(x, scale, qn, qp, grad_scale):
    """Learned-Step-size Quantization op (Esser et al. 2020; reference
    quant_layers.py LsqFunc): q = clip(round(x/s)) * s with the paper's
    straight-through gradients — d/dx passes inside the clip range,
    d/ds = g * (q/s - x/s rounded residual or the clip boundary)."""
    import jax

    @jax.custom_vjp
    def op(v, s):
        return jnp.clip(jnp.round(v / s), qn, qp) * s

    def fwd(v, s):
        return op(v, s), (v, s)

    def bwd(res, ct):
        v, s = res
        r = v / s
        inside = (r >= qn) & (r <= qp)
        dv = jnp.where(inside, ct, 0.0)
        q = jnp.clip(jnp.round(r), qn, qp)
        ds_elem = jnp.where(inside, q - r, q)
        full = ct * ds_elem * grad_scale
        # reduce to the scale's shape (per-tensor OR per-channel): with
        # the scale right-aligned against the input (numpy broadcasting),
        # sum exactly the axes the scale broadcasts across
        s_shape = jnp.shape(s)
        aligned = (1,) * (full.ndim - len(s_shape)) + tuple(s_shape)
        axes = tuple(i for i in range(full.ndim)
                     if aligned[i] == 1 and full.shape[i] != 1)
        return dv, full.sum(axis=axes).reshape(s_shape)

    op.defvjp(fwd, bwd)
    return op(x, scale)


def LsqFunc(x, scale, lsq_factor=1.0, bits=8, all_positive=False,
            per_channel=False):
    """Functional LSQ fake-quant (reference quant_layers.py LsqFunc)."""
    from paddle_tpu.core.dispatch import apply
    from paddle_tpu.core.tensor import Tensor as _T
    qn = 0 if all_positive else -(2 ** (bits - 1))
    qp = (2 ** bits - 1) if all_positive else (2 ** (bits - 1) - 1)
    return apply(lambda v, s: _lsq(v, s, qn, qp, lsq_factor), x,
                 scale if isinstance(scale, _T) else _T(jnp.asarray(scale)))


LsqPlusActFunc = LsqFunc


class FakeQuantWeightLSQPlus(Layer):
    """Weight fake-quant with a LEARNED step size (reference
    quant_layers.py FakeQuantWeightLSQPlus): scale initializes from the
    weight statistics and trains with the model."""

    def __init__(self, quant_bits=8, all_positive=False, channel_num=None,
                 per_channel=False, batch_init=20, dtype="float32",
                 quant_linear=False, reduce_type=None):
        super().__init__()
        self.bits = quant_bits
        self.all_positive = all_positive
        self.per_channel = per_channel
        if per_channel and not channel_num:
            raise ValueError("per_channel=True needs channel_num")
        self.scale = self.create_parameter(
            [channel_num] if per_channel else [1])
        # init-state rides in state_dict (a plain python flag would make
        # the first forward after set_state_dict clobber a restored
        # trained scale with fresh weight statistics)
        self.init_state = self.create_parameter([1])
        self.init_state._set_value(jnp.zeros((1,), jnp.float32))
        self.init_state.stop_gradient = True

    def forward(self, w):
        if float(self.init_state._value[0]) == 0.0:
            qp = (2 ** self.bits - 1) if self.all_positive \
                else (2 ** (self.bits - 1) - 1)
            wv = np.asarray(w._value)
            if self.per_channel:
                # per-LAST-axis channel statistics (scale right-aligns)
                axes = tuple(range(wv.ndim - 1))
                init = 2.0 * np.abs(wv).mean(axis=axes) / np.sqrt(qp)
                init = np.maximum(init, 1e-3).astype(np.float32)
                self.scale._set_value(jnp.asarray(init))
            else:
                init = 2.0 * float(np.abs(wv).mean()) / np.sqrt(qp) or 1e-3
                self.scale._set_value(jnp.asarray([init], jnp.float32))
            self.init_state._set_value(jnp.ones((1,), jnp.float32))
        qp_g = (2 ** self.bits - 1) if self.all_positive \
            else (2 ** (self.bits - 1) - 1)
        g = 1.0 / np.sqrt(np.prod(w.shape) * qp_g) if w.shape else 1.0
        return LsqFunc(w, self.scale, lsq_factor=float(g), bits=self.bits,
                       all_positive=self.all_positive,
                       per_channel=self.per_channel)


class FakeQuantActLSQPlus(FakeQuantWeightLSQPlus):
    """Activation LSQ+ fake-quant (learned scale + optional learned
    offset; offset omitted — symmetric activations on TPU)."""


class MovingAverageAbsMaxScale(FakeQuantMovingAverageAbsMax):
    """Observe-only: track the EMA absmax scale WITHOUT quantizing
    (reference quant_layers.py MovingAverageAbsMaxScale)."""

    def __init__(self, name=None, moving_rate=0.9, dtype="float32"):
        super().__init__(name=name, moving_rate=moving_rate,
                         observe_only=True)


MAOutputScaleLayer = FakeQuantMAOutputScaleLayer


class QuantizedLinear(Layer):
    """QAT linear: fake-quantizes weight (channel-wise) and activation
    (moving-average) around the float matmul (reference quant_layers.py
    QuantizedLinear); convert via paddle_tpu.quantization for the real
    int8 MXU kernel."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max", **kw):
        super().__init__()
        self._layer = layer
        if weight_quantize_type == "abs_max":
            self._wfq = FakeQuantAbsMax(quant_bits=weight_bits)
        else:
            self._wfq = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                   quant_axis=1)
        if activation_quantize_type == "abs_max":
            self._afq = FakeQuantAbsMax(quant_bits=activation_bits)
        else:
            self._afq = FakeQuantMovingAverageAbsMax(
                moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        w = self._wfq(self._layer.weight)
        return F.linear(self._afq(x), w, self._layer.bias)


class QuantizedConv2D(Layer):
    """QAT conv2d with fake-quantized weight + activation (reference
    quant_layers.py QuantizedConv2D)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max", **kw):
        super().__init__()
        self._layer = layer
        if weight_quantize_type == "abs_max":
            self._wfq = FakeQuantAbsMax(quant_bits=weight_bits)
        else:
            self._wfq = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                   quant_axis=0)
        if activation_quantize_type == "abs_max":
            self._afq = FakeQuantAbsMax(quant_bits=activation_bits)
        else:
            self._afq = FakeQuantMovingAverageAbsMax(
                moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        lay = self._layer
        w = self._wfq(lay.weight)
        return F.conv2d(self._afq(x), w, lay.bias,
                        stride=lay._stride, padding=lay._padding,
                        dilation=lay._dilation, groups=lay._groups,
                        data_format=lay._data_format)


class _QuantizedParallelLinear(QuantizedLinear):
    """QAT wrapper over fleet Column/RowParallelLinear: the wrapped
    layer's OWN forward runs (its _constrain sharding annotations,
    gather_output / input_is_parallel semantics and the tp psum must
    survive quantization) with the weight temporarily swapped for its
    fake-quantized view."""

    def forward(self, x):
        lay = self._layer
        w_float = lay.weight._value
        wq = self._wfq(lay.weight)
        try:
            lay.weight._value = wq._value
            return lay(self._afq(x))
        finally:
            lay.weight._value = w_float


class QuantizedColumnParallelLinear(_QuantizedParallelLinear):
    pass


class QuantizedRowParallelLinear(_QuantizedParallelLinear):
    pass


class QuantizedConv2DTranspose(Layer):
    """QAT transposed conv: fake-quantized weight + activation around the
    float conv2d_transpose (reference quant_layers.py:614)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max", **kw):
        super().__init__()
        self._layer = layer
        if weight_quantize_type == "abs_max":
            self._wfq = FakeQuantAbsMax(quant_bits=weight_bits)
        else:
            # transposed filters are [Cin, Cout/g, kh, kw]: channel axis 1
            self._wfq = FakeQuantChannelWiseAbsMax(quant_bits=weight_bits,
                                                   quant_axis=1)
        if activation_quantize_type == "abs_max":
            self._afq = FakeQuantAbsMax(quant_bits=activation_bits)
        else:
            self._afq = FakeQuantMovingAverageAbsMax(
                moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, x, output_size=None):
        from paddle_tpu.nn import functional as F
        lay = self._layer
        w = self._wfq(lay.weight)
        return F.conv2d_transpose(
            self._afq(x), w, lay.bias, stride=lay._stride,
            padding=lay._padding, output_padding=lay._output_padding,
            dilation=lay._dilation, groups=lay._groups,
            data_format=lay._data_format, output_size=output_size)


import jax as _jax


@_jax.custom_vjp
def _ste_round(v):
    import jax.numpy as jnp
    return jnp.round(v)


def _ste_round_fwd(v):
    return _ste_round(v), None


def _ste_round_bwd(_, ct):
    return (ct,)


_ste_round.defvjp(_ste_round_fwd, _ste_round_bwd)


def round(x):
    """Straight-through round (reference nn/quant/functional_layers.py):
    rounds in the forward, identity gradient in the backward — usable
    inside QAT graphs."""
    from paddle_tpu.core.dispatch import apply
    return apply(_ste_round, x)
