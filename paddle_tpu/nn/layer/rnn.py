"""Recurrent layers. Reference: python/paddle/nn/layer/rnn.py.

TPU-first: the time loop is a `lax.scan` (single compiled loop body, static
shapes) instead of the reference's per-timestep op dispatch / cuDNN RNN.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.container import LayerList
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.tensor import manipulation as M


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        from paddle_tpu.tensor.creation import full
        b = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(shape[0], (list, tuple)):
            return tuple(full([b] + list(s), init_value, dtype) for s in shape)
        return full([b] + list(shape), init_value, dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.input_size = input_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def fn(x, h, wi, wh, bi, bh):
            act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
            return act(x @ wi.T + bi + h @ wh.T + bh)
        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.input_size = input_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs, self.state_shape)
        h, c = states
        def fn(x, hv, cv, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hv @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            new_c = f * cv + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c
        new_h, new_c = apply(fn, inputs, h, c, self.weight_ih, self.weight_hh,
                             self.bias_ih, self.bias_hh)
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.input_size = input_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def fn(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * h
        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Run a cell over time with lax.scan."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            batch_ref = inputs if self.time_major else inputs
            initial_states = self.cell.get_initial_states(
                batch_ref, batch_dim_idx=1 if self.time_major else 0)
        # gather cell parameters for the scan-carried closure
        cell = self.cell
        params = {k: p for k, p in cell._parameters.items()}
        from paddle_tpu.core.dispatch import apply as _apply

        single_state = not isinstance(initial_states, (tuple, list))
        states_t = (initial_states,) if single_state else tuple(initial_states)
        param_names = list(params.keys())

        def fn(x, *rest):
            n_state = len(states_t)
            svals = rest[:n_state]
            pvals = dict(zip(param_names, rest[n_state:]))
            xm = jnp.swapaxes(x, 0, 1) if not self.time_major else x
            if self.is_reverse:
                xm = jnp.flip(xm, 0)

            def body(carry, xt):
                out_h, new_carry = _cell_pure(cell, xt, carry, pvals)
                return new_carry, out_h

            carry, outs = jax.lax.scan(body, tuple(svals), xm)
            if self.is_reverse:
                outs = jnp.flip(outs, 0)
            if not self.time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            return (outs,) + tuple(carry)

        res = _apply(fn, inputs, *states_t, *[params[k] for k in param_names])
        outs = res[0]
        final = res[1:]
        final_states = final[0] if single_state else tuple(final)
        return outs, final_states


def _cell_pure(cell, xt, carry, pvals):
    """Pure-array versions of the cell recurrences for use inside scan."""
    if isinstance(cell, LSTMCell):
        h, c = carry
        gates = xt @ pvals["weight_ih"].T + pvals["bias_ih"] + \
            h @ pvals["weight_hh"].T + pvals["bias_hh"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        new_c = f * c + i * g
        new_h = o * jnp.tanh(new_c)
        return new_h, (new_h, new_c)
    if isinstance(cell, GRUCell):
        (h,) = carry
        gi = xt @ pvals["weight_ih"].T + pvals["bias_ih"]
        gh = h @ pvals["weight_hh"].T + pvals["bias_hh"]
        ir, iz, ic = jnp.split(gi, 3, axis=-1)
        hr, hz, hc = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        c = jnp.tanh(ic + r * hc)
        new_h = (1 - z) * c + z * h
        return new_h, (new_h,)
    if isinstance(cell, SimpleRNNCell):
        (h,) = carry
        act = jnp.tanh if cell.activation == "tanh" else jax.nn.relu
        new_h = act(xt @ pvals["weight_ih"].T + pvals["bias_ih"] +
                    h @ pvals["weight_hh"].T + pvals["bias_hh"])
        return new_h, (new_h,)
    raise TypeError(f"unsupported cell {type(cell)}")


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            fw_states = bw_states = None
        else:
            fw_states, bw_states = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states, sequence_length)
        out = M.concat([out_fw, out_bw], axis=-1)
        return out, (st_fw, st_bw)


class _RNNBase(Layer):
    _cell_cls = None

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 **cell_kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.direction = direction
        layers = []
        for i in range(num_layers):
            in_size = input_size if i == 0 else hidden_size * self.num_directions
            if bidirect:
                layers.append(BiRNN(self._cell_cls(in_size, hidden_size, **cell_kwargs),
                                    self._cell_cls(in_size, hidden_size, **cell_kwargs),
                                    time_major))
            else:
                layers.append(RNN(self._cell_cls(in_size, hidden_size, **cell_kwargs),
                                  is_reverse=(direction == "backward"),
                                  time_major=time_major))
        self.layer_list = LayerList(layers)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        finals = []
        for i, rnn in enumerate(self.layer_list):
            st = None if initial_states is None else _layer_states(
                initial_states, i, self.num_directions, self._is_lstm())
            out, fs = rnn(out, st, sequence_length)
            finals.append(fs)
            if self.dropout > 0 and i < self.num_layers - 1:
                from paddle_tpu.nn.functional.common import dropout as _dropout
                out = _dropout(out, self.dropout, training=self.training)
        stacked = _stack_states(finals, self.num_directions, self._is_lstm())
        return out, stacked

    def _is_lstm(self):
        return self._cell_cls is LSTMCell


def _layer_states(initial_states, i, num_directions, is_lstm):
    if is_lstm:
        h, c = initial_states
        if num_directions == 2:
            return ((h[2 * i], c[2 * i]), (h[2 * i + 1], c[2 * i + 1]))
        return (h[i], c[i])
    h = initial_states
    if num_directions == 2:
        return (h[2 * i], h[2 * i + 1])
    return h[i]


def _stack_states(finals, num_directions, is_lstm):
    from paddle_tpu.tensor.manipulation import stack
    if is_lstm:
        hs, cs = [], []
        for fs in finals:
            if num_directions == 2:
                (h1, c1), (h2, c2) = fs
                hs += [h1, h2]
                cs += [c1, c2]
            else:
                h, c = fs
                hs.append(h)
                cs.append(c)
        return stack(hs, 0), stack(cs, 0)
    hs = []
    for fs in finals:
        if num_directions == 2:
            h1, h2 = fs
            hs += [h1, h2]
        else:
            hs.append(fs)
    return stack(hs, 0)


class SimpleRNN(_RNNBase):
    _cell_cls = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation)


class LSTM(_RNNBase):
    _cell_cls = LSTMCell


class GRU(_RNNBase):
    _cell_cls = GRUCell
