"""Norm layers. Reference: python/paddle/nn/layer/norm.py."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None
        self.bias = None
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features],
                attr=weight_attr if weight_attr is not True else None,
                default_initializer=I.Constant(1.0))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[num_features],
                attr=bias_attr if bias_attr is not True else None, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, self._dtype)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, self._dtype)))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (acts like BatchNorm2D with act support)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 data_format="NCHW", **kwargs):
        super().__init__(num_channels, momentum, epsilon, data_format=data_format)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW", **kw):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm.

    Reference: nn/layer/norm.py SyncBatchNorm (NCCL allreduce of stats).
    TPU-native: inside pjit/shard_map the mean/var reduction becomes an XLA
    AllReduce over the `dp` mesh axis automatically when the batch axis is
    sharded — so plain batch_norm with psum'd statistics. Single-process
    eager mode falls back to local stats.
    """

    def forward(self, input):
        from paddle_tpu.distributed import mesh as dmesh
        axis = dmesh.current_collective_axis()
        if axis is None:
            return super().forward(input)
        # Under shard_map: psum batch statistics across the dp axis.
        import jax
        from paddle_tpu.core.dispatch import apply
        from paddle_tpu.core.engine import no_grad
        ca = 1 if self._data_format.startswith("NC") else -1

        def fn(v, w, b):
            axes = tuple(i for i in range(v.ndim) if i != ca % v.ndim)
            cnt = np.prod([v.shape[i] for i in axes])
            s = jax.lax.psum(jnp.sum(v, axis=axes), axis)
            ss = jax.lax.psum(jnp.sum(v * v, axis=axes), axis)
            n = jax.lax.psum(jnp.asarray(cnt, jnp.float32), axis)
            mean = s / n
            var = ss / n - mean * mean
            shape = [1] * v.ndim
            shape[ca % v.ndim] = -1
            out = (v - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + self._epsilon)
            if w is not None:
                out = out * w.reshape(shape)
            if b is not None:
                out = out + b.reshape(shape)
            return out, mean, var
        out, mean_t, var_t = apply(fn, input, self.weight, self.bias)
        if self.training:
            with no_grad():
                m = self._momentum
                self._mean._set_value(m * self._mean._value + (1 - m) * mean_t._value)
                self._variance._set_value(m * self._variance._value + (1 - m) * var_t._value)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None, fused=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        # fused=True routes through the Pallas fused LN kernel even off
        # TPU (interpret mode), False forces the pure-JAX composition,
        # None follows F.set_fused_norm / the platform default
        self._fused = fused
        self.weight = None
        self.bias = None
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=self._normalized_shape,
                attr=weight_attr if weight_attr is not True else None,
                default_initializer=I.Constant(1.0))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=self._normalized_shape,
                attr=bias_attr if bias_attr is not True else None, is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon, fused=self._fused)


class RMSNorm(Layer):
    """TPU-friendly RMSNorm (used by LLM blocks; pallas fused kernel backs
    the hot path)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None, fused=None):
        super().__init__()
        self._epsilon = epsilon
        self._fused = fused
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon,
                          fused=self._fused)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None
        self.bias = None
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_channels],
                attr=weight_attr if weight_attr is not True else None,
                default_initializer=I.Constant(1.0))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[num_channels],
                attr=bias_attr if bias_attr is not True else None, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None
        self.bias = None
        if weight_attr is not False:
            self.weight = self.create_parameter(
                shape=[num_features],
                attr=weight_attr if weight_attr is not True else None,
                default_initializer=I.Constant(1.0))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[num_features],
                attr=bias_attr if bias_attr is not True else None, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", Tensor(
            jnp.asarray(np.random.default_rng(0).normal(size=h), jnp.float32)))
        self.register_buffer("weight_v", Tensor(
            jnp.asarray(np.random.default_rng(1).normal(size=w), jnp.float32)))

    def forward(self, weight):
        return F.spectral_norm(weight, self.weight_u, self.weight_v, self._dim,
                               self._power_iters, self._epsilon)
