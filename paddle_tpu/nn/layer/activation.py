"""Activation layers. Reference: python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


def _simple(fname, **defaults):
    class _Act(Layer):
        def __init__(self, *args, name=None, **kwargs):
            super().__init__()
            merged = dict(defaults)
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                merged[keys[i]] = a
            merged.update({k: v for k, v in kwargs.items() if k in merged})
            self._kwargs = merged

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)
    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
GELU = _simple("gelu", approximate=False)
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
LogSigmoid = _simple("log_sigmoid")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Hardtanh = _simple("hardtanh", min=-1.0, max=1.0)
ELU = _simple("elu", alpha=1.0)
CELU = _simple("celu", alpha=1.0)
SELU = _simple("selu", scale=1.0507009873554805, alpha=1.6732632423543772)
LeakyReLU = _simple("leaky_relu", negative_slope=0.01)
Hardshrink = _simple("hardshrink", threshold=0.5)
Softshrink = _simple("softshrink", threshold=0.5)
Tanhshrink = _simple("tanhshrink")
Softplus = _simple("softplus", beta=1, threshold=20)
Softsign = _simple("softsign")
Swish = _simple("swish")
Silu = _simple("silu")
Mish = _simple("mish")
ThresholdedReLU = _simple("thresholded_relu", threshold=1.0)
GLU = _simple("glu", axis=-1)
RReLU = _simple("rrelu", lower=0.125, upper=1.0 / 3.0)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups = groups
        self.axis = axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs
    (reference nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        if len(x.shape) not in (3, 4):
            raise ValueError("Softmax2D expects 3-D or 4-D input")
        return F.softmax(x, axis=-3)
