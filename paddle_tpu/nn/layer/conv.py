"""Conv layers. Reference: python/paddle/nn/layer/conv.py."""
from __future__ import annotations

import math

import numpy as np

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


def _ntuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) else tuple(int(x) for x in v)


class _ConvNd(Layer):
    _nd = 2
    _transpose = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 output_padding=0):
        super().__init__()
        nd = self._nd
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = _ntuple(stride, nd)
        self._padding = padding
        self._padding_mode = padding_mode
        self._dilation = _ntuple(dilation, nd)
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        if self._transpose:
            w_shape = [in_channels, out_channels // groups] + list(self._kernel_size)
        else:
            w_shape = [out_channels, in_channels // groups] + list(self._kernel_size)
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        std = math.sqrt(2.0 / fan_in)  # paddle conv default: MSRA-style normal
        self.weight = self.create_parameter(
            shape=w_shape, attr=weight_attr,
            default_initializer=None if (weight_attr and getattr(weight_attr, "initializer", None))
            else I.Normal(0.0, std))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_channels], attr=bias_attr if bias_attr is not True else None,
                is_bias=True)


class Conv1D(_ConvNd):
    _nd = 1

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    _nd = 2

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={list(self._kernel_size)}, stride={list(self._stride)}")


class Conv3D(_ConvNd):
    _nd = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    _nd = 1
    _transpose = True

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    _nd = 2
    _transpose = True

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    _nd = 3
    _transpose = True

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
