"""Transformer layers. Reference: python/paddle/nn/layer/transformer.py.

MultiHeadAttention keeps paddle's API (including cache for incremental
decode) but routes the core attention through scaled_dot_product_attention,
whose hot path is the Pallas flash-attention kernel on TPU.
"""
from __future__ import annotations

import collections

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.common import Dropout, Linear
from paddle_tpu.nn.layer.container import LayerList
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu.nn.layer.norm import LayerNorm
from paddle_tpu.tensor import manipulation as M


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self.q_proj(query)
        b, s = q.shape[0], q.shape[1]
        q = M.reshape(q, [b, s, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key)
            v = self.v_proj(value)
            k = M.reshape(k, [b, k.shape[1], self.num_heads, self.head_dim])
            v = M.reshape(v, [b, v.shape[1], self.num_heads, self.head_dim])
        if isinstance(cache, self.Cache):
            k = M.concat([cache.k, k], axis=1)
            v = M.concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return q, k, v, cache

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self.k_proj(key)
            v = self.v_proj(value if value is not None else key)
            b = k.shape[0]
            k = M.reshape(k, [b, k.shape[1], self.num_heads, self.head_dim])
            v = M.reshape(v, [b, v.shape[1], self.num_heads, self.head_dim])
            return self.StaticCache(k, v)
        from paddle_tpu.tensor.creation import zeros
        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim], dtype="float32")
        v = zeros([b, 0, self.num_heads, self.head_dim], dtype="float32")
        return self.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0,
            training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = M.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


def _get_activation(name):
    return getattr(F, name)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 fused_ln=False):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        # fused_ln=True collapses each post-LN residual join into the
        # Pallas fused_ln_residual kernel (interpret mode off-TPU)
        self._fused_ln = fused_ln
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = activation

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        if not self.normalize_before:
            src = _residual_ln(self.norm1, residual, self.dropout1(src),
                               self._fused_ln, "norm1")
        else:
            src = residual + self.dropout1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(_get_activation(self.activation)(
            self.linear1(src))))
        if not self.normalize_before:
            src = _residual_ln(self.norm2, residual, self.dropout2(src),
                               self._fused_ln, "norm2")
        else:
            src = residual + self.dropout2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            type(encoder_layer)(**_clone_args(encoder_layer))
            for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, c = mod(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


def _residual_ln(norm, residual, delta, fused, scope_name):
    """Post-LN residual join: ``norm(residual + delta)``.

    With the fused flag (and an affine norm) the add and the norm
    collapse into the Pallas fused_add_layer_norm kernel — one HBM
    pass, custom VJP recomputing the stats, y-only return (post-norm
    blocks never consume the raw sum, so backward pays no zeros
    cotangent for it) — under the norm's scope name so roofline rows
    keep their pre-fusion identity."""
    if fused and norm.weight is not None:
        from paddle_tpu.core.dispatch import apply
        from paddle_tpu.observability.profile import layer_scope
        from paddle_tpu.ops.pallas.norm import fused_add_layer_norm
        with layer_scope(scope_name):
            return apply(lambda a, r, w, b: fused_add_layer_norm(
                a, r, w, b, norm._epsilon), delta, residual,
                norm.weight, norm.bias)
    return norm(residual + delta)


def _clone_args(layer):
    if isinstance(layer, TransformerEncoderLayer):
        return dict(
            d_model=layer.self_attn.embed_dim, nhead=layer.self_attn.num_heads,
            dim_feedforward=layer.linear1.weight.shape[1],
            dropout=layer.dropout1.p, activation=layer.activation,
            attn_dropout=layer.self_attn.dropout, act_dropout=layer.dropout.p,
            normalize_before=layer.normalize_before,
            fused_ln=layer._fused_ln)
    if isinstance(layer, TransformerDecoderLayer):
        return dict(
            d_model=layer.self_attn.embed_dim, nhead=layer.self_attn.num_heads,
            dim_feedforward=layer.linear1.weight.shape[1],
            dropout=layer.dropout1.p, activation=layer.activation,
            attn_dropout=layer.self_attn.dropout, act_dropout=layer.dropout.p,
            normalize_before=layer.normalize_before,
            fused_ln=layer._fused_ln)
    raise TypeError(type(layer))


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 fused_ln=False):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self._fused_ln = fused_ln
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = activation

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
            incremental_cache = None
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        if not self.normalize_before:
            tgt = _residual_ln(self.norm1, residual, self.dropout1(tgt),
                               self._fused_ln, "norm1")
        else:
            tgt = residual + self.dropout1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
            static_cache = None
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory,
                                                memory_mask, cache[1])
        if not self.normalize_before:
            tgt = _residual_ln(self.norm2, residual, self.dropout2(tgt),
                               self._fused_ln, "norm2")
        else:
            tgt = residual + self.dropout2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(_get_activation(self.activation)(
            self.linear1(tgt))))
        if not self.normalize_before:
            tgt = _residual_ln(self.norm3, residual, self.dropout3(tgt),
                               self._fused_ln, "norm3")
        else:
            tgt = residual + self.dropout3(tgt)
        if cache is None:
            return tgt
        return tgt, (incremental_cache, static_cache)

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory,
                                           MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            type(decoder_layer)(**_clone_args(decoder_layer))
            for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, c = mod(output, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [l.gen_cache(memory) for l in self.layers]
        if do_zip:
            return list(zip(*caches))
        return caches


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from paddle_tpu.tensor.creation import full, tril
        import paddle_tpu as P
        m = P.full([length, length], float("-inf"), dtype="float32")
        return P.triu(m, diagonal=1)
