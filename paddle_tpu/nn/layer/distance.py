"""Distance layers. Reference: python/paddle/nn/layer/distance.py."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)
