"""Pooling layers. Reference: python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from paddle_tpu.nn import functional as F
from paddle_tpu.nn.layer.layers import Layer


class _Pool(Layer):
    def __init__(self, kernel_size=None, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kw = kwargs


class MaxPool1D(_Pool):
    def forward(self, x):
        return F.max_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("return_mask", False),
                            self.kw.get("ceil_mode", False))


class MaxPool2D(_Pool):
    def forward(self, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("return_mask", False),
                            self.kw.get("ceil_mode", False),
                            self.kw.get("data_format", "NCHW"))


class MaxPool3D(_Pool):
    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("return_mask", False),
                            self.kw.get("ceil_mode", False),
                            self.kw.get("data_format", "NCDHW"))


class AvgPool1D(_Pool):
    def forward(self, x):
        return F.avg_pool1d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("exclusive", True),
                            self.kw.get("ceil_mode", False))


class AvgPool2D(_Pool):
    def forward(self, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("ceil_mode", False),
                            self.kw.get("exclusive", True),
                            self.kw.get("divisor_override"),
                            self.kw.get("data_format", "NCHW"))


class AvgPool3D(_Pool):
    def forward(self, x):
        return F.avg_pool3d(x, self.kernel_size, self.stride, self.padding,
                            self.kw.get("ceil_mode", False),
                            self.kw.get("exclusive", True),
                            self.kw.get("divisor_override"),
                            self.kw.get("data_format", "NCDHW"))


class _AdaptivePool(Layer):
    def __init__(self, output_size, return_mask=False, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask
        self.kw = kwargs


class AdaptiveAvgPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     self.kw.get("data_format", "NCHW"))


class AdaptiveAvgPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     self.kw.get("data_format", "NCDHW"))


class AdaptiveMaxPool1D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool2D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(_AdaptivePool):
    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class MaxUnPool1D(_Pool):
    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size=self.kw.get("output_size"))


class MaxUnPool2D(_Pool):
    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size=self.kw.get("output_size"))


class MaxUnPool3D(_Pool):
    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, self.kernel_size, self.stride,
                              self.padding, output_size=self.kw.get("output_size"))
