"""Common layers. Reference: python/paddle/nn/layer/common.py."""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dtype import convert_dtype
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, input):
        return input


class Linear(Layer):
    """y = xW + b, W: [in_features, out_features] (reference layout;
    maps directly to an MXU matmul)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._dtype_ = self._dtype
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=None if (weight_attr and getattr(weight_attr, "initializer", None))
            else I.XavierNormal())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                shape=[out_features], attr=bias_attr if bias_attr is not True else None,
                is_bias=True)
        self.name = name

    def forward(self, input):
        return F.linear(input, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.weight.shape[0]}, out_features={self.weight.shape[1]}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, input):
        from paddle_tpu.tensor.manipulation import flatten
        return flatten(input, self.start_axis, self.stop_axis)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, input):
        return F.dropout(input, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout2d(input, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, input):
        return F.dropout3d(input, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, input):
        return F.alpha_dropout(input, self.p, training=self.training)


class Embedding(Layer):
    """Reference: nn/layer/common.py Embedding. weight: [num_emb, dim].
    On a TPU mesh the weight can be sharded over the `tp` axis
    (VocabParallelEmbedding in distributed/fleet/meta_parallel)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self._sparse = sparse
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0) if not (
                weight_attr and getattr(weight_attr, "initializer", None)) else None)
        if padding_idx is not None:
            v = self.weight._value.at[padding_idx].set(0.0)
            self.weight._set_value(v)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx,
                           sparse=self._sparse)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "nearest",
                             data_format=self.data_format)


class UpsamplingBilinear2D(Layer):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, "bilinear",
                             align_corners=True, data_format=self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(shape=[1, out_features],
                                              attr=None, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class _PadNd(Layer):
    _format = "NCHW"

    def __init__(self, padding, mode="constant", value=0.0, data_format=None,
                 name=None):
        super().__init__()
        self.padding = padding if isinstance(padding, (list, tuple)) else [padding] * (
            2 * self._spatial)
        self.mode = mode
        self.value = value
        self.data_format = data_format or self._format

    def forward(self, x):
        return F.pad(x, list(self.padding), self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    _spatial = 1
    _format = "NCL"


class Pad2D(_PadNd):
    _spatial = 2
    _format = "NCHW"


class Pad3D(_PadNd):
    _spatial = 3
    _format = "NCDHW"


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from paddle_tpu.tensor.manipulation import reshape
        s = x.shape
        new = s[:self.axis] + list(self.shape) + s[self.axis + 1:]
        return reshape(x, new)
