"""nn.Layer base class.

Reference parity: python/paddle/fluid/dygraph/layers.py (paddle.nn.Layer).
Holds Parameters (registered in the global state registry so to_static can
lift them), buffers (e.g. BatchNorm running stats — updated by value rebind,
captured functionally under jit), sublayers, hooks, train/eval mode.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import convert_dtype, get_default_dtype
from paddle_tpu.core.tensor import Parameter, Tensor
from paddle_tpu.framework.state import register_state_tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.observability.profile import layer_scope as _layer_scope


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype=None):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = [0]

    # ---- attribute magic ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            # the child's attribute name under THIS parent: the unique
            # component its profiler scope path is built from.  First
            # registration wins — a shared instance mounted under two
            # parents keeps ONE stable component (call-site paths still
            # differ through the ambient scope stack)
            value.__dict__.setdefault("_local_name", name)
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, value)
                    return
                params[name] = value
                return
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
                buffers.pop(name)
            if layers is not None and name in layers and value is None:
                layers.pop(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ---- construction helpers ----
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        dtype = convert_dtype(dtype) or self._dtype
        init = default_initializer
        pa = attr if isinstance(attr, I.ParamAttr) else None
        if pa is not None and pa.initializer is not None:
            init = pa.initializer
        if pa is None or pa.initializer is None:
            # set_global_initializer: overrides the layer's built-in
            # default but never an explicit ParamAttr initializer
            g = I._global_default(is_bias)
            if g is not None:
                init = g
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierUniform()
        shape = tuple(int(s) for s in shape)
        p = Parameter(jnp.zeros(shape, dtype), name=pa.name if pa else None)
        if pa is not None:
            p.optimize_attr = {"learning_rate": pa.learning_rate}
            p.regularizer = pa.regularizer
            p.trainable = pa.trainable
            p.need_clip = pa.need_clip
        init(p)
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        if isinstance(sublayer, Layer):
            sublayer.__dict__.setdefault("_local_name", str(name))
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.persistable = persistable
            register_state_tensor(tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- traversal ----
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and sub is not self:
                continue
            for pname, p in sub._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and sub is not self:
                continue
            for bname, b in sub._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def children(self):
        return (l for _, l in self.named_children())

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True,
                                           layers_set=layers_set)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ---- mode ----
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, sub in self.named_sublayers(include_self=True):
            for bname, b in sub._buffers.items():
                if b is None or bname in sub._non_persistable_buffer_names:
                    continue
                full = f"{name}.{bname}" if name else bname
                dest[structured_name_prefix + full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(val.shape) != tuple(tgt._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {val.shape} vs {tgt._value.shape}")
            tgt._set_value(val.astype(tgt._value.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- dtype / device ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = convert_dtype(dtype)
            self._dtype = dtype
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._set_value(p._value.astype(dtype))
            for b in self.buffers():
                if jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._set_value(b._value.astype(dtype))
        if device is not None:
            import jax as _jax
            from paddle_tpu.core.device import CPUPlace, TPUPlace
            place = device
            if isinstance(device, str):
                place = CPUPlace(0) if device.startswith("cpu") else TPUPlace(0)
            for t in list(self.parameters()) + list(self.buffers()):
                t._set_value(_jax.device_put(t._value, place.jax_device))
        return self

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # ---- hooks ----
    def register_forward_pre_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_pre_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id[0])

    def register_forward_post_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_post_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id[0])

    # ---- call ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def enable_recompute(self, mode=True):
        """Per-Layer remat selection (ROADMAP item 5, bytes half):
        ``True`` recomputes this layer's forward in backward whenever it
        trains under gradients; ``"auto"`` only when an ambient
        ``amp`` remat policy is active (``to_static(remat=...)``);
        ``False`` turns it off.  Boundary activations are saved in bf16
        under ``remat="bf16"`` (see amp/policy.py).  Nested remat is
        not re-wrapped — the outermost recompute region wins."""
        if mode not in (True, False, "auto"):
            raise ValueError(f"mode must be True/False/'auto', got {mode!r}")
        self.__dict__["_remat_mode"] = mode
        return self

    def __call__(self, *inputs, **kwargs):
        mode = self.__dict__.get("_remat_mode")
        if mode and self.training:
            from paddle_tpu.amp import policy as _amppol
            from paddle_tpu.core import engine as _engine
            from paddle_tpu.distributed.recompute import (recompute,
                                                          recompute_active)
            if _engine.is_grad_enabled() and not recompute_active() \
                    and (mode is True or _amppol.remat_active()):
                return recompute(self, *inputs, **kwargs)
        from paddle_tpu.amp.policy import current_policy as _cur_policy
        pol = _cur_policy()
        if pol is not None and pol.dtype is not None:
            # bf16 activation residency: the f32->bf16 convert happens at
            # the FIRST layer boundary an f32 activation crosses; every
            # layer downstream sees bf16 and keeps it (params are not
            # inputs here and stay f32 master weights)
            inputs = tuple(pol.cast_input(t) for t in inputs)
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        # jax.named_scope threading: under a to_static/jit trace every
        # eqn this forward emits carries the layer-tree path on its name
        # stack (and jax keeps it through jvp/transpose, so the layer's
        # BACKWARD eqns attribute to the same scope) — the attribution
        # key observability.profile's roofline reports aggregate by
        with _layer_scope(self._scope_name()):
            outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def _scope_name(self):
        """This layer's component on the profiler scope path: its
        attribute name under the parent (unique among siblings); a bare
        container index gets the class prefix (``gptdecoderlayer_0``);
        an unregistered root falls back to ``_name_scope``."""
        local = self.__dict__.get("_local_name")
        if local is None:
            return self._name_scope
        if local.isdigit():
            return f"{self._name_scope}_{local}"
        return local

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + l for l in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


def enable_static():
    """No-op: paddle_tpu is always dygraph; @to_static gives graph mode."""


def disable_static():
    """No-op (dygraph is the default and only interpreter mode)."""


def in_declarative_mode():
    return False
