"""paddle_tpu.nn — layers namespace. Reference: python/paddle/nn/__init__.py."""
from paddle_tpu.nn import functional  # noqa: F401
from paddle_tpu.nn import initializer  # noqa: F401
from paddle_tpu.nn import quant  # noqa: F401
from paddle_tpu.nn.decode import (  # noqa: F401
    BeamSearchDecoder,
    dynamic_decode,
)
from paddle_tpu.nn import utils  # noqa: F401
from paddle_tpu.nn.clip import (  # noqa: F401
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from paddle_tpu.nn.initializer import ParamAttr  # noqa: F401
from paddle_tpu.nn.layer.activation import *  # noqa: F401,F403
from paddle_tpu.nn.layer.common import *  # noqa: F401,F403
from paddle_tpu.nn.layer.container import (  # noqa: F401
    LayerDict,
    LayerList,
    ParameterList,
    Sequential,
)
from paddle_tpu.nn.layer.conv import (  # noqa: F401
    Conv1D,
    Conv1DTranspose,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
)
from paddle_tpu.nn.layer.distance import PairwiseDistance  # noqa: F401
from paddle_tpu.nn.layer.layers import Layer  # noqa: F401
from paddle_tpu.nn.layer.loss import *  # noqa: F401,F403
from paddle_tpu.nn.layer.norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from paddle_tpu.nn.layer.pooling import *  # noqa: F401,F403
from paddle_tpu.nn.layer.rnn import (  # noqa: F401
    GRU,
    GRUCell,
    LSTM,
    LSTMCell,
    RNN,
    BiRNN,
    RNNCellBase,
    SimpleRNN,
    SimpleRNNCell,
)
from paddle_tpu.nn.layer.transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from paddle_tpu.nn.layer.vision import (  # noqa: F401
    ChannelShuffle,
    PixelShuffle,
    PixelUnshuffle,
)
