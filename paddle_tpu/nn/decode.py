"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode + gather_tree.

Reference parity: python/paddle/nn/decode.py re-exporting
fluid/layers/rnn.py (BeamSearchDecoder :939, dynamic_decode further
down) and nn/functional/extension.py gather_tree :253.

TPU-native: the decode loop runs step-wise over cached-jit ops (each
step is one compiled program; beam bookkeeping is jnp one-hots/gathers),
ending with a gather_tree backtrace. Batch-first layout like the
reference's dynamic_decode outputs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode", "gather_tree"]


def gather_tree(ids, parents):
    """Backtrace beam-search results (reference
    nn/functional/extension.py:253): ids/parents are
    [max_time, batch, beam]; walk parents from the last step so row b,
    beam k holds the FULL selected sequence."""

    def fn(idv, pav):
        t, b, k = idv.shape

        def step(beams, ti):
            # beams: [b, k] current beam index at time ti+1's viewpoint
            cur_ids = jnp.take_along_axis(idv[ti], beams, axis=1)
            prev = jnp.take_along_axis(pav[ti], beams, axis=1)
            return prev.astype(beams.dtype), cur_ids

        init = jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32), (b, k))
        _, rev = jax.lax.scan(step, init, jnp.arange(t - 1, -1, -1))
        return jnp.flip(rev, axis=0)

    return apply(fn, ids if isinstance(ids, Tensor) else Tensor(jnp.asarray(ids)),
                 parents if isinstance(parents, Tensor)
                 else Tensor(jnp.asarray(parents)))


def _tile_beam(x, beam_size):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    tiled = jnp.repeat(v, beam_size, axis=0)
    return Tensor(tiled)


class BeamSearchDecoder:
    """Beam-search wrapper over an RNN cell (reference fluid rnn.py:939).

    embedding_fn maps ids -> cell inputs; output_fn maps cell outputs ->
    vocab logits. States are any pytree of Tensors with batch on axis 0.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch*beam, ...] (reference helper)."""
        return _tile_beam(x, beam_size)

    # --- decode protocol -------------------------------------------------
    def initialize(self, initial_cell_states):
        k = self.beam_size
        states = jax.tree_util.tree_map(
            lambda t: _tile_beam(t, k), initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        leaves = jax.tree_util.tree_leaves(
            states, is_leaf=lambda t: isinstance(t, Tensor))
        bk = leaves[0].shape[0]
        batch = bk // k
        ids = jnp.full((bk,), self.start_token, jnp.int32)
        # beam 0 starts live, beams 1.. start at -inf so step 1 expands
        # from a single hypothesis per batch row
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (k - 1), jnp.float32), (batch,))
        finished = jnp.zeros((bk,), bool)
        return Tensor(ids), states, Tensor(log_probs), Tensor(finished)

    def step(self, ids, states, log_probs, finished):
        k = self.beam_size
        inputs = self.embedding_fn(ids) if self.embedding_fn else ids
        cell_out, new_states = self.cell(inputs, states)
        logits = self.output_fn(cell_out) if self.output_fn else cell_out

        def fn(lg, lp, fin):
            bk, vocab = lg.shape
            batch = bk // k
            step_lp = jax.nn.log_softmax(lg.astype(jnp.float32), -1)
            # finished beams only extend with end_token at zero cost
            fin_mask = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
            step_lp = jnp.where(fin[:, None], fin_mask[None, :], step_lp)
            total = lp[:, None] + step_lp                  # [bk, vocab]
            total = total.reshape(batch, k * vocab)
            top_lp, top_idx = jax.lax.top_k(total, k)      # [batch, k]
            parent = (top_idx // vocab).astype(jnp.int32)  # beam within row
            word = (top_idx % vocab).astype(jnp.int32)
            gather = (jnp.arange(batch, dtype=jnp.int32)[:, None] * k
                      + parent).reshape(-1)
            new_fin = fin[gather] | (word.reshape(-1) == self.end_token)
            return (word.reshape(-1), top_lp.reshape(-1), new_fin, gather,
                    parent.reshape(-1))

        word, lp, fin, gather, parent = apply(
            fn, logits, log_probs, finished)
        gathered_states = jax.tree_util.tree_map(
            lambda t: apply(lambda sv, gv: sv[gv], t, gather),
            new_states, is_leaf=lambda t: isinstance(t, Tensor))
        return word, gathered_states, lp, fin, parent


def dynamic_decode(decoder, inits=None, max_step_num=None, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Run `decoder` until every beam emits end_token or max_step_num
    (reference dynamic_decode). Returns (ids [batch, time, beam] int64,
    final log_probs [batch, beam]) (+ sequence lengths with
    return_length), with the gather_tree backtrace applied."""
    assert inits is not None, \
        "inits is required: the initial cell states (any pytree of " \
        "Tensors with batch on axis 0)"
    assert max_step_num is not None and max_step_num > 0, \
        "max_step_num is required (static bounds keep programs compiled)"
    ids, states, log_probs, finished = decoder.initialize(inits)
    step_ids, step_parents = [], []
    for _ in range(max_step_num):
        ids, states, log_probs, finished, parent = decoder.step(
            ids, states, log_probs, finished)
        step_ids.append(ids)
        step_parents.append(parent)
        if bool(np.asarray(jax.device_get(finished._value)).all()):
            break

    k = decoder.beam_size
    bk = step_ids[0].shape[0]
    batch = bk // k
    t = len(step_ids)
    ids_tbk = Tensor(jnp.stack([s._value for s in step_ids])
                     .reshape(t, batch, k))
    par_tbk = Tensor(jnp.stack([p._value for p in step_parents])
                     .reshape(t, batch, k))
    traced = gather_tree(ids_tbk, par_tbk)          # [t, batch, k]
    out = apply(lambda v: jnp.transpose(v, (1, 0, 2)).astype(jnp.int64),
                traced)
    lp = Tensor(log_probs._value.reshape(batch, k))
    lengths = None
    if return_length:
        # lengths come from the BATCH-MAJOR view (time axis 1); compute
        # before any time-major re-transpose
        lengths = apply(
            lambda v: jnp.minimum(
                jnp.argmax((v == decoder.end_token).astype(jnp.int32),
                           axis=1) + 1,
                v.shape[1]) * jnp.any(v == decoder.end_token, 1)
            + v.shape[1] * (1 - jnp.any(v == decoder.end_token, 1)),
            out)
    if output_time_major:
        out = apply(lambda v: jnp.transpose(v, (1, 0, 2)), out)
    if return_length:
        return out, lp, lengths
    return out, lp
