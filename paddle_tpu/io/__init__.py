"""Data loading. Reference: python/paddle/io/__init__.py + fluid dataloader.

TPU-first data path: the DataLoader keeps a background thread pool for
batch assembly + an async host→device staging step (double buffering), which
plays the role of the reference's C++ multiprocess DataLoaderIter: keep the
accelerator fed so step time is never input-bound.
"""
from __future__ import annotations

import itertools
import queue
import threading

import numpy as np

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import _rng


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self.cum[-1])

    def __getitem__(self, idx):
        di = int(np.searchsorted(self.cum, idx, side="right"))
        prev = 0 if di == 0 else int(self.cum[di - 1])
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(np.floor(n * l)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    total = sum(lengths)
    perm = np.random.default_rng(_rng.seed_val).permutation(total)
    out = []
    offset = 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng()
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        idx = rng.choice(len(self.weights), self.num_samples,
                         replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shard-aware sampler. Reference: python/paddle/io/__init__.py
    DistributedBatchSampler. On TPU the `rank` is the process index of a
    multi-host jax.distributed run (data parallel over DCN)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            from paddle_tpu import distributed as dist
            num_replicas = dist.get_world_size()
        if rank is None:
            from paddle_tpu import distributed as dist
            rank = dist.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(n)
        indices = np.concatenate([indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._value) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _collate_numpy(batch):
    """default_collate_fn's structure, NUMPY leaves only — the worker-
    process collate (a forked child must never touch JAX/XLA: the
    parent's runtime threads don't survive the fork)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return type(sample)(_collate_numpy([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _collate_numpy([b[k] for b in batch]) for k in sample}
    return batch


def _tree_map_np(obj, fn):
    if isinstance(obj, np.ndarray):
        return fn(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_tree_map_np(o, fn) for o in obj)
    if isinstance(obj, dict):
        return {k: _tree_map_np(v, fn) for k, v in obj.items()}
    return obj


def _shm_pack(obj):
    """numpy leaves -> shared-memory descriptors (zero pickle-copy for
    the bulk bytes; reference use_shared_memory semantics)."""
    from multiprocessing import shared_memory
    blocks = []

    def pack(a):
        a = np.ascontiguousarray(a)
        if a.nbytes == 0:
            return ("__np__", a)
        shm = shared_memory.SharedMemory(create=True, size=a.nbytes)
        np.ndarray(a.shape, a.dtype, buffer=shm.buf)[...] = a
        name = shm.name
        # ownership transfers to the CONSUMER (parent unlinks after the
        # copy); drop this process's resource_tracker registration or
        # every worker shutdown spews leaked-segment warnings
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        blocks.append(shm)
        return ("__shm__", name, a.shape, str(a.dtype))

    out = _tree_map_np(obj, pack)
    # close OUR handles (the segment lives until the parent unlinks)
    for b in blocks:
        b.close()
    return out


def _shm_unpack(obj):
    from multiprocessing import shared_memory

    def unpack(o):
        if isinstance(o, tuple) and o and o[0] == "__shm__":
            _, name, shape, dtype = o
            shm = shared_memory.SharedMemory(name=name)
            try:
                return np.array(np.ndarray(shape, dtype, buffer=shm.buf))
            finally:
                shm.close()
                shm.unlink()
        if isinstance(o, tuple) and o and o[0] == "__np__":
            return o[1]
        if isinstance(o, (list, tuple)):
            return type(o)(unpack(x) for x in o)
        if isinstance(o, dict):
            return {k: unpack(v) for k, v in o.items()}
        return o

    return unpack(obj)


def _shm_release(obj):
    """Unlink every shm descriptor in a payload WITHOUT copying it
    (cleanup for batches the consumer never took)."""
    from multiprocessing import shared_memory
    if isinstance(obj, tuple) and obj and obj[0] == "__shm__":
        try:
            shm = shared_memory.SharedMemory(name=obj[1])
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        return
    if isinstance(obj, (list, tuple)):
        for o in obj:
            _shm_release(o)
    elif isinstance(obj, dict):
        for o in obj.values():
            _shm_release(o)


def _process_worker_loop(dataset, wid, num_workers, idx_q, res_q,
                         use_shm, worker_init_fn, default_collate):
    """Worker-process main (reference fluid/dataloader/worker.py
    _worker_loop): fetch index batches, run __getitem__ + transforms,
    collate to numpy, ship via shared memory. No JAX in here."""
    import traceback as _tb
    _worker_info.info = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn is not None:
        try:
            worker_init_fn(wid)
        except Exception:
            res_q.put((-1, "err", _tb.format_exc()))
            return
    while True:
        task = idx_q.get()
        if task is None:
            return
        i, idxs = task
        try:
            items = [dataset[j] for j in idxs]
            data = _collate_numpy(items) if default_collate else items
            payload = _shm_pack(data) if use_shm else data
            res_q.put((i, "ok", payload))
        except Exception:
            res_q.put((i, "err", _tb.format_exc()))


class DataLoader:
    """Reference: python/paddle/io/dataloader. Three batch-producing
    paths, fastest applicable wins:
      1. native C++ prefetch ring (array-backed datasets, libptdata);
      2. REAL worker processes (r5, reference dataloader_iter.py +
         worker.py): map-style datasets whose samples are numpy/python —
         __getitem__ + transforms run GIL-free in forked children,
         batches return through shared memory, the parent converts to
         device tensors;
      3. threaded prefetch (iterable datasets, tensor-producing
         datasets, or use_process_workers=False)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
        self.drop_last = drop_last
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_process_workers = use_process_workers
        self._native_loader = None
        self._native_src_ids = None
        self._native_active = False

    def _process_mode(self):
        """Resolve whether num_workers>0 means PROCESSES here. Explicit
        flag wins; AUTO probes one sample — numpy/python samples go to
        forked workers, tensor-producing datasets stay on threads (a
        forked child must not touch the parent's XLA runtime, and
        device-array datasets gain nothing from escaping the GIL)."""
        if self.num_workers <= 0 or self._iterable_mode:
            return False
        if self.use_process_workers is not None:
            return bool(self.use_process_workers)
        cached = getattr(self, "_process_mode_cache", None)
        if cached is not None:
            return cached
        import multiprocessing
        if "fork" not in multiprocessing.get_all_start_methods():
            self._process_mode_cache = False
            return False
        try:
            first = next(iter(self.batch_sampler))[0]
            sample = self.dataset[first]
        except Exception:
            self._process_mode_cache = False
            return False
        ok = [True]

        def chk(o):
            if isinstance(o, (np.ndarray, int, float, str, bytes,
                              np.integer, np.floating)):
                return
            if isinstance(o, (list, tuple)):
                for x in o:
                    chk(x)
                return
            if isinstance(o, dict):
                for x in o.values():
                    chk(x)
                return
            ok[0] = False

        chk(sample)
        self._process_mode_cache = ok[0]
        return ok[0]

    def _iter_process_workers(self):
        """Reference dataloader_iter._DataLoaderIterMultiProcess: forked
        workers + shared-memory results + ordered reassembly."""
        import multiprocessing
        ctx = multiprocessing.get_context("fork")
        batches = list(self.batch_sampler)
        cap = self.prefetch_factor * self.num_workers
        idx_q = ctx.Queue()
        res_q = ctx.Queue()
        default_collate = self.collate_fn is default_collate_fn
        use_shm = self.use_shared_memory
        procs = [ctx.Process(
            target=_process_worker_loop,
            args=(self.dataset, w, self.num_workers, idx_q, res_q,
                  use_shm, self.worker_init_fn, default_collate),
            daemon=True) for w in range(self.num_workers)]
        import warnings as _warnings
        with _warnings.catch_warnings():
            # the interpreter warns that fork + multithreaded JAX can
            # deadlock; our children never touch JAX (numpy-only worker
            # loop, enforced by the _process_mode sample probe), which
            # is the same contract torch/paddle fork workers run under
            _warnings.simplefilter("ignore", RuntimeWarning)
            for p in procs:
                p.start()
        # bound BEFORE the try: the finally block below reads `results`,
        # and an exception while dispatching the first batches must
        # surface as itself, not as a masking NameError
        results = {}
        try:
            sent = 0
            for i, b in enumerate(batches[:cap]):
                idx_q.put((i, list(b)))
                sent += 1
            for i in range(len(batches)):
                while i not in results:
                    try:
                        j, status, payload = res_q.get(
                            timeout=self.timeout or 5.0)
                    except queue.Empty:
                        if self.timeout:
                            raise RuntimeError(
                                f"DataLoader worker timed out after "
                                f"{self.timeout}s")
                        if not any(p.is_alive() for p in procs) and \
                                res_q.empty():
                            raise RuntimeError(
                                "DataLoader worker processes died "
                                "unexpectedly")
                        continue
                    if status == "err":
                        raise RuntimeError(
                            f"DataLoader worker raised:\n{payload}")
                    results[j] = payload
                    if sent < len(batches):
                        idx_q.put((sent, list(batches[sent])))
                        sent += 1
                payload = results.pop(i)
                data = _shm_unpack(payload) if use_shm else payload
                if default_collate:
                    yield _tree_map_np(data, Tensor)
                else:
                    yield self.collate_fn(data)
        finally:
            for _ in procs:
                idx_q.put(None)
            for p in procs:
                p.join(timeout=2.0)
            for p in procs:
                if p.is_alive():
                    p.terminate()
            if use_shm:
                # early close / worker error: in-flight payloads hold
                # shm segments the workers UNREGISTERED (ownership was
                # handed to us) — unlink them or they outlive the
                # process and accumulate in /dev/shm
                leftovers = list(results.values())
                while True:
                    try:
                        _, status, payload = res_q.get_nowait()
                    except queue.Empty:
                        break
                    except (OSError, ValueError):
                        break
                    if status == "ok":
                        leftovers.append(payload)
                for payload in leftovers:
                    try:
                        _shm_release(payload)
                    except Exception:
                        pass

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size or 1))
                if not batch:
                    return
                if len(batch) < (self.batch_size or 1) and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    # ---- native (C++) fast path ----
    def _native_arrays(self):
        """Contiguous host arrays backing the dataset, or None. Datasets can
        opt in by defining native_arrays() (only valid when __getitem__ does
        no per-sample Python transform work)."""
        if self.collate_fn is not default_collate_fn:
            return None
        if hasattr(self.dataset, "native_arrays"):
            try:
                arrays = [np.asarray(a) for a in self.dataset.native_arrays()]
            except Exception:
                return None
        elif isinstance(self.dataset, TensorDataset):
            try:
                arrays = [np.asarray(t._value if isinstance(t, Tensor) else t)
                          for t in self.dataset.tensors]
            except Exception:
                return None
        else:
            return None
        # zero-copy only: a contiguity COPY would silently freeze the data
        # (in-place mutation visible on the Python path, stale here)
        if any(not a.flags["C_CONTIGUOUS"] for a in arrays):
            return None
        return arrays

    def _native_iter(self):
        """C++ epoch pipeline (shuffle+gather+prefetch off-GIL) when the
        dataset is array-backed and the sampling pattern is expressible
        (plain sequential/shuffled full-epoch BatchSampler)."""
        from paddle_tpu import native
        if not native.available() or self._iterable_mode:
            return None
        bs = self.batch_sampler
        if type(bs) is not BatchSampler:
            return None
        if type(bs.sampler) is SequenceSampler:
            shuffle = False
        elif type(bs.sampler) is RandomSampler and \
                not bs.sampler.replacement and bs.sampler._num_samples is None:
            shuffle = True
        else:
            return None
        srcs = self._native_sources()
        if srcs is None:
            return None
        rebuild = self._native_loader is None or \
            self._native_src_ids is None or \
            len(srcs) != len(self._native_src_ids) or \
            any(a is not b for a, b in zip(srcs, self._native_src_ids))
        if rebuild and self._native_active:
            return None   # can't swap the loader under a live iterator
        if rebuild:
            # (re)build when the backing tensors were rebound — keeps the
            # native path semantics aligned with the Python path, which
            # re-reads the dataset every epoch
            arrays = self._native_arrays()
            if arrays is None or arrays[0].shape[0] == 0:
                return None
            if self._native_loader is not None:
                self._native_loader.close()
            # match the Python path's shuffle entropy: deterministic only
            # when the user explicitly seeded the framework
            seed = _rng.seed_val if _rng.seeded else int(
                np.random.SeedSequence().entropy & ((1 << 63) - 1))
            self._native_loader = native.NativeLoader(
                arrays, bs.batch_size, seed=seed, shuffle=shuffle,
                drop_last=bs.drop_last, nthreads=self.num_workers or None)
            self._native_src_ids = srcs   # strong refs: identity is stable

        def gen():
            # claim the native stream at FIRST consumption (not creation):
            # a second live iterator falls back to the Python path instead
            # of resetting the shared producer mid-epoch
            if self._native_active:
                yield from self._iter_batches()
                return
            self._native_active = True
            try:
                for bufs in self._native_loader:
                    yield tuple(Tensor(b) for b in bufs)
            finally:
                self._native_active = False
        return gen()

    def _native_sources(self):
        """The dataset's backing buffer objects (STRONG refs — identity
        comparison detects rebinds; holding them prevents id reuse).
        None = not array-backed."""
        if self.collate_fn is not default_collate_fn:
            return None
        if hasattr(self.dataset, "native_arrays"):
            try:
                return list(self.dataset.native_arrays())
            except Exception:
                return None
        if isinstance(self.dataset, TensorDataset):
            return [t._value if isinstance(t, Tensor) else t
                    for t in self.dataset.tensors]
        return None

    def __iter__(self):
        nat = self._native_iter()
        if nat is not None:
            yield from nat
            return
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        if self._process_mode():
            yield from self._iter_process_workers()
            return
        # threaded prefetch: bounded queue keeps up to prefetch_factor *
        # num_workers batches in flight
        q = queue.Queue(maxsize=self.prefetch_factor * self.num_workers)
        sentinel = object()

        if self._iterable_mode:
            def producer():
                _worker_info.info = WorkerInfo(0, self.num_workers, self.dataset)
                try:
                    for b in self._iter_batches():
                        q.put(b)
                finally:
                    q.put(sentinel)
            threads = [threading.Thread(target=producer, daemon=True)]
            n_sentinels = 1
        else:
            idx_q = queue.Queue()
            batches = list(self.batch_sampler)
            for i, b in enumerate(batches):
                idx_q.put((i, b))
            results = {}
            res_lock = threading.Condition()
            # backpressure: at most prefetch_factor * num_workers finished
            # batches buffered ahead of the consumer
            slots = threading.Semaphore(self.prefetch_factor * self.num_workers)

            def worker(wid):
                _worker_info.info = WorkerInfo(wid, self.num_workers, self.dataset)
                while True:
                    # acquire BEFORE pulling an index so the K in-flight slots
                    # always cover the K smallest unproduced indices — the
                    # consumer's next batch is guaranteed to be in flight
                    slots.acquire()
                    try:
                        i, idx_batch = idx_q.get_nowait()
                    except queue.Empty:
                        slots.release()
                        return
                    data = self.collate_fn([self.dataset[j] for j in idx_batch])
                    with res_lock:
                        results[i] = data
                        res_lock.notify_all()

            threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                       for w in range(self.num_workers)]
            for t in threads:
                t.start()
            # ordered consumption
            for i in range(len(batches)):
                with res_lock:
                    while i not in results:
                        res_lock.wait()
                    data = results.pop(i)
                slots.release()
                yield data
            for t in threads:
                t.join()
            return

        for t in threads:
            t.start()
        done = 0
        while done < n_sentinels:
            item = q.get()
            if item is sentinel:
                done += 1
                continue
            yield item
        for t in threads:
            t.join()
