// libptdata — native data-pipeline core for paddle_tpu.
//
// Reference parity: the reference's C++ DataLoader machinery
// (paddle/fluid/operators/reader/blocking_queue.h, buffered_reader.cc,
// python/paddle/fluid/dataloader worker processes): background workers
// assemble batches ahead of the consumer so the accelerator never waits on
// input. TPU-native twist: instead of per-sample Python workers we run the
// WHOLE epoch pipeline (shuffle -> shard slice -> multithreaded row gather
// -> prefetch ring) in C++ threads with no GIL, for any dataset whose
// storage is contiguous host arrays (TensorDataset, the vision datasets).
//
// Exposed C ABI (ctypes-friendly):
//   ptdata_shuffle            Fisher-Yates over an int64 index array
//   ptdata_shard_indices      epoch shuffle + pad + rank slice
//   ptdata_gather             multithreaded row gather (memcpy)
//   ptdata_loader_*           background epoch loader with prefetch ring
//
// Build: make -C paddle_tpu/native  (g++ -O3 -shared -fPIC -pthread)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// RNG: splitmix64 (deterministic across platforms, seedable from Python)
// ---------------------------------------------------------------------------
static inline uint64_t splitmix64(uint64_t* state) {
  uint64_t z = (*state += 0x9e3779b97f4a7c15ULL);
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

void ptdata_shuffle(int64_t* indices, int64_t n, uint64_t seed) {
  uint64_t st = seed;
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(splitmix64(&st) % (uint64_t)(i + 1));
    int64_t tmp = indices[i];
    indices[i] = indices[j];
    indices[j] = tmp;
  }
}

// Fill `out` (length ceil(n/nranks)) with this rank's epoch indices:
// permutation of [0,n) (if shuffle), padded by wrapping, strided by rank.
// Mirrors DistributedBatchSampler's index math.
void ptdata_shard_indices(int64_t n, uint64_t seed, int shuffle,
                          int64_t nranks, int64_t rank, int64_t* out) {
  int64_t per = (n + nranks - 1) / nranks;
  std::vector<int64_t> idx(n);
  for (int64_t i = 0; i < n; ++i) idx[i] = i;
  if (shuffle) ptdata_shuffle(idx.data(), n, seed);
  for (int64_t k = 0; k < per; ++k) {
    int64_t pos = rank + k * nranks;  // strided slice of padded permutation
    out[k] = idx[pos % n];            // pad by cycling (pad can exceed n)
  }
}

// ---------------------------------------------------------------------------
// Multithreaded row gather: dst[i] = src[indices[i]] (row_bytes each)
// ---------------------------------------------------------------------------
static void gather_range(const char* src, int64_t row_bytes,
                         const int64_t* indices, int64_t lo, int64_t hi,
                         char* dst) {
  for (int64_t i = lo; i < hi; ++i)
    memcpy(dst + i * row_bytes, src + indices[i] * row_bytes,
           (size_t)row_bytes);
}

void ptdata_gather(const void* src, int64_t row_bytes, const int64_t* indices,
                   int64_t n, void* dst, int nthreads) {
  const char* s = (const char*)src;
  char* d = (char*)dst;
  if (nthreads <= 1 || n < nthreads * 4) {
    gather_range(s, row_bytes, indices, 0, n, d);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    ts.emplace_back(gather_range, s, row_bytes, indices, lo, hi, d);
  }
  for (auto& t : ts) t.join();
}

// ---------------------------------------------------------------------------
// Loader: producer thread gathers batches into a prefetch ring
// ---------------------------------------------------------------------------
struct Slot {
  std::vector<char> data;   // concatenated per-array batch bytes
  int64_t rows = 0;
  bool filled = false;
};

struct Loader {
  std::vector<const char*> srcs;
  std::vector<int64_t> row_bytes;      // per array
  int64_t n_rows, batch_size;
  bool shuffle, drop_last;
  int64_t nranks, rank;
  int nthreads;
  uint64_t seed;

  std::vector<int64_t> order;          // this rank's epoch indices
  int64_t n_batches = 0;

  std::vector<Slot> slots;             // prefetch ring
  size_t head = 0, tail = 0, count = 0;
  std::mutex mu;
  std::condition_variable nonfull, nonempty;
  bool stop = false;
  std::thread producer;

  int64_t slot_bytes_per_row() const {
    int64_t s = 0;
    for (auto rb : row_bytes) s += rb;
    return s;
  }

  void build_order() {
    int64_t per = (n_rows + nranks - 1) / nranks;
    order.resize(per);
    ptdata_shard_indices(n_rows, seed, shuffle ? 1 : 0, nranks, rank,
                         order.data());
    n_batches = drop_last ? per / batch_size
                          : (per + batch_size - 1) / batch_size;
  }

  void produce() {
    int64_t per = (int64_t)order.size();
    for (int64_t b = 0; b < n_batches; ++b) {
      int64_t lo = b * batch_size;
      int64_t hi = lo + batch_size < per ? lo + batch_size : per;
      int64_t rows = hi - lo;
      std::unique_lock<std::mutex> lk(mu);
      nonfull.wait(lk, [&] { return count < slots.size() || stop; });
      if (stop) return;
      Slot& slot = slots[head];
      lk.unlock();
      // gather outside the lock — this is the heavy, GIL-free work
      char* dst = slot.data.data();
      for (size_t a = 0; a < srcs.size(); ++a) {
        ptdata_gather(srcs[a], row_bytes[a], order.data() + lo, rows, dst,
                      nthreads);
        dst += row_bytes[a] * batch_size;
      }
      slot.rows = rows;
      lk.lock();
      slot.filled = true;
      head = (head + 1) % slots.size();
      ++count;
      nonempty.notify_one();
    }
    std::unique_lock<std::mutex> lk(mu);
    // sentinel: rows == 0 marks epoch end
    nonfull.wait(lk, [&] { return count < slots.size() || stop; });
    if (stop) return;
    slots[head].rows = 0;
    slots[head].filled = true;
    head = (head + 1) % slots.size();
    ++count;
    nonempty.notify_one();
  }
};

void* ptdata_loader_create(const void** srcs, const int64_t* row_bytes,
                           int narrays, int64_t n_rows, int64_t batch_size,
                           uint64_t seed, int shuffle, int drop_last,
                           int64_t nranks, int64_t rank, int nthreads,
                           int prefetch) {
  Loader* L = new Loader();
  for (int a = 0; a < narrays; ++a) {
    L->srcs.push_back((const char*)srcs[a]);
    L->row_bytes.push_back(row_bytes[a]);
  }
  L->n_rows = n_rows;
  L->batch_size = batch_size;
  L->shuffle = shuffle != 0;
  L->drop_last = drop_last != 0;
  L->nranks = nranks < 1 ? 1 : nranks;
  L->rank = rank;
  L->nthreads = nthreads < 1 ? 1 : nthreads;
  L->seed = seed;
  L->build_order();
  int nslots = prefetch < 2 ? 2 : prefetch;
  L->slots.resize(nslots);
  for (auto& s : L->slots)
    s.data.resize((size_t)(L->slot_bytes_per_row() * batch_size));
  L->producer = std::thread(&Loader::produce, L);
  return L;
}

int64_t ptdata_loader_num_batches(void* h) {
  return ((Loader*)h)->n_batches;
}

// Pop the next batch into caller buffers (one per array, batch-sized).
// Returns rows in the batch; 0 at epoch end.
int64_t ptdata_loader_next(void* h, void** dsts) {
  Loader* L = (Loader*)h;
  std::unique_lock<std::mutex> lk(L->mu);
  L->nonempty.wait(lk, [&] { return L->count > 0 || L->stop; });
  if (L->stop) return -1;
  Slot& slot = L->slots[L->tail];
  int64_t rows = slot.rows;
  lk.unlock();
  if (rows > 0) {
    const char* src = slot.data.data();
    for (size_t a = 0; a < L->srcs.size(); ++a) {
      memcpy(dsts[a], src, (size_t)(L->row_bytes[a] * rows));
      src += L->row_bytes[a] * L->batch_size;
    }
  }
  lk.lock();
  slot.filled = false;
  L->tail = (L->tail + 1) % L->slots.size();
  --L->count;
  L->nonfull.notify_one();
  return rows;
}

// Start a new epoch (reshuffle with a fresh seed). Joins the old producer.
void ptdata_loader_reset(void* h, uint64_t seed) {
  Loader* L = (Loader*)h;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stop = true;
    L->nonfull.notify_all();
    L->nonempty.notify_all();
  }
  if (L->producer.joinable()) L->producer.join();
  L->stop = false;
  L->head = L->tail = 0;
  L->count = 0;
  for (auto& s : L->slots) s.filled = false;
  L->seed = seed;
  L->build_order();
  L->producer = std::thread(&Loader::produce, L);
}

void ptdata_loader_destroy(void* h) {
  Loader* L = (Loader*)h;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stop = true;
    L->nonfull.notify_all();
    L->nonempty.notify_all();
  }
  if (L->producer.joinable()) L->producer.join();
  delete L;
}


// ---------------------------------------------------------------------------
// Fused image augmentation: zero-pad -> random crop -> random hflip ->
// normalize (per-channel mean/std) -> float32 HWC or CHW, threaded over the
// batch. Reference parity: the per-sample Python transform chain
// (python/paddle/vision/transforms RandomCrop+RandomHorizontalFlip+
// Normalize+ToTensor) that the reference runs inside C++-backed DataLoader
// worker processes; here it is one GIL-free pass per batch.
// ---------------------------------------------------------------------------
static void augment_range(const uint8_t* src, int64_t h, int64_t w,
                          int64_t c, float* dst, int64_t out_h,
                          int64_t out_w, int pad, int random_crop,
                          int random_flip, const float* mean,
                          const float* stdev, int to_chw, uint64_t seed,
                          int64_t lo, int64_t hi) {
  const int64_t in_img = h * w * c;
  const int64_t out_img = out_h * out_w * c;
  for (int64_t i = lo; i < hi; ++i) {
    uint64_t st = seed + 0x9e3779b97f4a7c15ULL * (uint64_t)(i + 1);
    int64_t max_y = h + 2 * pad - out_h;
    int64_t max_x = w + 2 * pad - out_w;
    int64_t off_y = 0, off_x = 0;
    if (random_crop && max_y >= 0 && max_x >= 0) {
      off_y = (int64_t)(splitmix64(&st) % (uint64_t)(max_y + 1));
      off_x = (int64_t)(splitmix64(&st) % (uint64_t)(max_x + 1));
    } else {
      off_y = max_y > 0 ? max_y / 2 : 0;   // center crop fallback
      off_x = max_x > 0 ? max_x / 2 : 0;
    }
    int flip = random_flip && (splitmix64(&st) & 1);
    const uint8_t* img = src + i * in_img;
    float* out = dst + i * out_img;
    for (int64_t y = 0; y < out_h; ++y) {
      int64_t sy = y + off_y - pad;               // padded-space -> source
      for (int64_t x = 0; x < out_w; ++x) {
        int64_t ox = flip ? (out_w - 1 - x) : x;
        int64_t sx = x + off_x - pad;
        for (int64_t ch = 0; ch < c; ++ch) {
          float v = 0.0f;                          // zero padding
          if (sy >= 0 && sy < h && sx >= 0 && sx < w)
            v = (float)img[(sy * w + sx) * c + ch];
          v = (v / 255.0f - mean[ch]) / stdev[ch];
          if (to_chw)
            out[ch * out_h * out_w + y * out_w + ox] = v;
          else
            out[(y * out_w + ox) * c + ch] = v;
        }
      }
    }
  }
}

void ptdata_augment_batch(const uint8_t* src, int64_t n, int64_t h,
                          int64_t w, int64_t c, float* dst, int64_t out_h,
                          int64_t out_w, int pad, int random_crop,
                          int random_flip, const float* mean,
                          const float* stdev, int to_chw, uint64_t seed,
                          int nthreads) {
  if (nthreads <= 1 || n < nthreads * 2) {
    augment_range(src, h, w, c, dst, out_h, out_w, pad, random_crop,
                  random_flip, mean, stdev, to_chw, seed, 0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    ts.emplace_back(augment_range, src, h, w, c, dst, out_h, out_w, pad,
                    random_crop, random_flip, mean, stdev, to_chw, seed,
                    lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
