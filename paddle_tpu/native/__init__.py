"""Native (C++) runtime: GIL-free data-pipeline core (libptdata.so).

Reference parity: the reference's C++ dataloader stack
(paddle/fluid/operators/reader/blocking_queue.h, buffered_reader.cc and the
fluid dataloader worker processes). Here the native side owns the whole
epoch pipeline — shuffle, shard slicing, multithreaded row gather, prefetch
ring — for datasets backed by contiguous host arrays; Python only wraps the
popped buffers as Tensors.

The library compiles on first use (g++, ~1s) and is cached next to the
source; everything degrades gracefully to the pure-Python path when a
toolchain isn't available (`available()` -> False).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libptdata.so")
_lib = None
_lock = threading.Lock()
_build_err = None


def _build_and_load(src_name, so_path):
    """Shared build-or-load: (re)compile when the .so is missing/stale,
    then dlopen. Raises on toolchain/load failure (callers decide the
    fallback policy)."""
    src = os.path.join(_DIR, src_name)
    if not os.path.exists(so_path) or (
            os.path.getmtime(so_path) < os.path.getmtime(src)):
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-shared",
             "-o", so_path, src],
            check=True, capture_output=True)
    return ctypes.CDLL(so_path)


def _load():
    global _lib, _build_err
    with _lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            lib = _build_and_load("ptdata.cc", _SO)
        except Exception as e:  # no toolchain / load failure -> Python path
            _build_err = e
            return None
        lib.ptdata_shuffle.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
        lib.ptdata_shard_indices.argtypes = [
            ctypes.c_int64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p]
        lib.ptdata_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int]
        lib.ptdata_loader_create.restype = ctypes.c_void_p
        lib.ptdata_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int]
        lib.ptdata_loader_num_batches.restype = ctypes.c_int64
        lib.ptdata_loader_num_batches.argtypes = [ctypes.c_void_p]
        lib.ptdata_loader_next.restype = ctypes.c_int64
        lib.ptdata_loader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)]
        lib.ptdata_loader_reset.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ptdata_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.ptdata_augment_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int]
        _lib = lib
        return _lib


def available():
    return _load() is not None


def shuffle_indices(n, seed):
    """Deterministic Fisher-Yates permutation of arange(n) in C++."""
    lib = _load()
    idx = np.arange(n, dtype=np.int64)
    if lib is None:
        return np.random.default_rng(seed).permutation(n)
    lib.ptdata_shuffle(idx.ctypes.data_as(ctypes.c_void_p), n, seed)
    return idx


def shard_indices(n, seed, shuffle, nranks, rank):
    """This rank's epoch indices (shuffled, padded, strided) — the
    DistributedBatchSampler index math, natively."""
    lib = _load()
    per = (n + nranks - 1) // nranks
    out = np.empty(per, dtype=np.int64)
    if lib is None:
        idx = np.arange(n)
        if shuffle:
            idx = np.random.default_rng(seed).permutation(n)
        idx = np.resize(idx, per * nranks)  # pad by cycling, like the C++
        return idx[rank::nranks].astype(np.int64)
    lib.ptdata_shard_indices(n, seed, 1 if shuffle else 0, nranks, rank,
                             out.ctypes.data_as(ctypes.c_void_p))
    return out


def gather_rows(src, indices, nthreads=None):
    """dst[i] = src[indices[i]] with multithreaded memcpy (no GIL)."""
    lib = _load()
    src = np.ascontiguousarray(src)
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    if lib is None:
        return src[indices]
    out = np.empty((len(indices),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    nthreads = nthreads or min(8, os.cpu_count() or 1)
    lib.ptdata_gather(src.ctypes.data_as(ctypes.c_void_p), row_bytes,
                      indices.ctypes.data_as(ctypes.c_void_p), len(indices),
                      out.ctypes.data_as(ctypes.c_void_p), nthreads)
    return out


def augment_batch(images, out_size, pad=0, random_crop=False,
                  random_flip=False, mean=0.0, std=1.0, to_chw=True,
                  seed=0, nthreads=None):
    """Fused native augmentation: zero-pad -> (random|center) crop ->
    random hflip -> /255 -> normalize -> float32 CHW/HWC, threaded over
    the batch with no GIL. images: uint8 [N, H, W, C]. Falls back to a
    numpy implementation when the native library is unavailable."""
    images = np.ascontiguousarray(images, dtype=np.uint8)
    n, h, w, c = images.shape
    oh, ow = (out_size, out_size) if isinstance(out_size, int) else out_size
    mean = np.ascontiguousarray(mean, np.float32).reshape(-1)
    std = np.ascontiguousarray(std, np.float32).reshape(-1)
    if mean.size == 1:
        mean = np.repeat(mean, c)
    if std.size == 1:
        std = np.repeat(std, c)
    if mean.size != c or std.size != c:
        raise ValueError(
            f"mean/std must have {c} entries (or 1), got "
            f"{mean.size}/{std.size}")
    lib = _load()
    if lib is not None:
        shape = (n, c, oh, ow) if to_chw else (n, oh, ow, c)
        out = np.empty(shape, np.float32)
        nthreads = nthreads or min(8, os.cpu_count() or 1)
        lib.ptdata_augment_batch(
            images.ctypes.data_as(ctypes.c_void_p), n, h, w, c,
            out.ctypes.data_as(ctypes.c_void_p), oh, ow, int(pad),
            int(bool(random_crop)), int(bool(random_flip)),
            mean.ctypes.data_as(ctypes.c_void_p),
            std.ctypes.data_as(ctypes.c_void_p), int(bool(to_chw)),
            ctypes.c_uint64(seed), nthreads)
        return out
    # numpy fallback: same semantics (incl. randomness), python-speed
    rng = np.random.default_rng(seed)
    padded = np.zeros((n, h + 2 * pad, w + 2 * pad, c), np.float32)
    padded[:, pad:pad + h, pad:pad + w] = images
    max_y = max(h + 2 * pad - oh, 0)
    max_x = max(w + 2 * pad - ow, 0)
    out = np.empty((n, oh, ow, c), np.float32)
    for i in range(n):
        oy = int(rng.integers(0, max_y + 1)) if random_crop else max_y // 2
        ox = int(rng.integers(0, max_x + 1)) if random_crop else max_x // 2
        crop = padded[i, oy:oy + oh, ox:ox + ow]
        if random_flip and rng.integers(0, 2):
            crop = crop[:, ::-1]
        out[i] = crop
    outv = (out / 255.0 - mean) / std
    return outv.transpose(0, 3, 1, 2).copy() if to_chw else outv


class NativeLoader:
    """Background C++ epoch loader over contiguous arrays.

    arrays: list of np.ndarray sharing dim 0 (the sample dim). Iterating
    yields tuples of np.ndarray batches, assembled and prefetched by the
    native producer thread. Not thread-safe; one iterator at a time.
    """

    def __init__(self, arrays, batch_size, seed=0, shuffle=False,
                 drop_last=False, nranks=1, rank=0, nthreads=None,
                 prefetch=4):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"libptdata unavailable: {_build_err}")
        self._lib = lib
        self.arrays = [np.ascontiguousarray(a) for a in arrays]
        n = self.arrays[0].shape[0]
        if any(a.shape[0] != n for a in self.arrays):
            raise ValueError("arrays must share dim 0")
        self.batch_size = int(batch_size)
        self.n_rows = n
        self._row_bytes = [
            a.itemsize * int(np.prod(a.shape[1:], dtype=np.int64))
            for a in self.arrays]
        srcs = (ctypes.c_void_p * len(self.arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self.arrays])
        rbs = (ctypes.c_int64 * len(self.arrays))(*self._row_bytes)
        self._h = lib.ptdata_loader_create(
            srcs, rbs, len(self.arrays), n, self.batch_size, seed,
            1 if shuffle else 0, 1 if drop_last else 0, nranks, rank,
            nthreads or min(8, os.cpu_count() or 1), prefetch)
        self._epoch_seed = seed
        self._dirty = False   # producer mid-epoch (iterator abandoned early)

    def __len__(self):
        return self._lib.ptdata_loader_num_batches(self._h)

    def __iter__(self):
        # every __iter__ starts a FULL epoch (matching the Python path): if a
        # previous iterator was abandoned mid-epoch, restart the producer
        if self._dirty:
            self._epoch_seed += 1
            self._lib.ptdata_loader_reset(self._h, self._epoch_seed)
        self._dirty = True
        while True:
            bufs = [np.empty((self.batch_size,) + a.shape[1:], dtype=a.dtype)
                    for a in self.arrays]
            ptrs = (ctypes.c_void_p * len(bufs))(
                *[b.ctypes.data_as(ctypes.c_void_p).value for b in bufs])
            rows = self._lib.ptdata_loader_next(self._h, ptrs)
            if rows <= 0:
                self._epoch_seed += 1
                self._lib.ptdata_loader_reset(self._h, self._epoch_seed)
                self._dirty = False
                return
            yield tuple(b[:rows] for b in bufs)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.ptdata_loader_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------- PS sparse table
_PSTABLE_SO = os.path.join(_DIR, "libpstable.so")
_pstable_lib = None
_pstable_err = None


def _pstable():
    """Load (building on first use) the native PS table kernels; None
    when no toolchain is available (callers fall back to numpy)."""
    global _pstable_lib, _pstable_err
    with _lock:
        if _pstable_lib is not None or _pstable_err is not None:
            return _pstable_lib
        try:
            lib = _build_and_load("pstable.cc", _PSTABLE_SO)
            lib.pstable_pull.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int]
            lib.pstable_push.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_float,
                ctypes.c_float, ctypes.c_int]
            _pstable_lib = lib
        except Exception as e:
            _pstable_err = e
            return None
        return _pstable_lib


def pstable_available():
    return _pstable() is not None


def pstable_pull(data, ids, row_offset, n_threads=4):
    """data [R, D] float32 (C-contiguous), ids int64 any shape ->
    [*ids.shape, D] float32 (zeros for out-of-shard rows)."""
    lib = _pstable()
    ids = np.ascontiguousarray(ids, np.int64)
    flat = ids.reshape(-1)
    out = np.empty((flat.size, data.shape[1]), np.float32)
    lib.pstable_pull(
        data.ctypes.data_as(ctypes.c_void_p), data.shape[0], data.shape[1],
        flat.ctypes.data_as(ctypes.c_void_p), flat.size, row_offset,
        out.ctypes.data_as(ctypes.c_void_p), n_threads)
    return out.reshape(ids.shape + (data.shape[1],))


def pstable_push(data, acc, ids, grads, row_offset, lr, eps, optimizer):
    """In-place merged sparse update; optimizer 'sgd'|'adagrad'."""
    lib = _pstable()
    ids = np.ascontiguousarray(np.asarray(ids).reshape(-1), np.int64)
    grads = np.ascontiguousarray(
        np.asarray(grads, np.float32).reshape(ids.size, data.shape[1]))
    lib.pstable_push(
        data.ctypes.data_as(ctypes.c_void_p),
        acc.ctypes.data_as(ctypes.c_void_p) if acc is not None else None,
        data.shape[0], data.shape[1],
        ids.ctypes.data_as(ctypes.c_void_p), ids.size, row_offset,
        grads.ctypes.data_as(ctypes.c_void_p), lr, eps,
        1 if optimizer == "adagrad" else 0)
