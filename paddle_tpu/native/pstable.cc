// Native parameter-server sparse-table kernels.
//
// Reference parity: the reference PS runs its table ops in C++ brpc
// services (paddle/fluid/distributed/ps/table/memory_sparse_table.cc);
// here the same hot paths — row gather (pull) and merged sparse
// optimizer update (push) — run natively and GIL-free under
// jax.pure_callback / io_callback, multithreaded for the pull.
//
// Build: g++ -O3 -std=c++17 -fPIC -pthread -shared -o libpstable.so pstable.cc
#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cmath>
#include <numeric>
#include <thread>
#include <vector>

extern "C" {

// rows[i] = data[ids[i] - row_offset] when in-shard else 0
// data: [local_rows, dim] float32; ids: [n] int64; out: [n, dim] float32
void pstable_pull(const float* data, int64_t local_rows, int64_t dim,
                  const int64_t* ids, int64_t n, int64_t row_offset,
                  float* out, int n_threads) {
  auto work = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t loc = ids[i] - row_offset;
      float* dst = out + i * dim;
      if (loc >= 0 && loc < local_rows) {
        std::memcpy(dst, data + loc * dim, sizeof(float) * dim);
      } else {
        std::memset(dst, 0, sizeof(float) * dim);
      }
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  if (nt == 1 || n < 1024) {
    work(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo < hi) threads.emplace_back(work, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// Merged sparse update: duplicate ids inside the batch are summed FIRST
// (the PS sparse-merge semantics — matters for adagrad, where the
// accumulator update uses the merged gradient squared), then one
// optimizer step per unique row.
//   optimizer: 0 = sgd, 1 = adagrad (acc required)
void pstable_push(float* data, float* acc, int64_t local_rows, int64_t dim,
                  const int64_t* ids, int64_t n, int64_t row_offset,
                  const float* grads, float lr, float eps, int optimizer) {
  // sort positions by local row id to group duplicates
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return ids[a] < ids[b];
  });
  std::vector<float> merged(dim);
  int64_t i = 0;
  while (i < n) {
    int64_t row = ids[order[i]];
    int64_t loc = row - row_offset;
    std::fill(merged.begin(), merged.end(), 0.0f);
    int64_t j = i;
    for (; j < n && ids[order[j]] == row; ++j) {
      const float* g = grads + order[j] * dim;
      for (int64_t d = 0; d < dim; ++d) merged[d] += g[d];
    }
    if (loc >= 0 && loc < local_rows) {
      float* w = data + loc * dim;
      if (optimizer == 1) {
        float* a = acc + loc * dim;
        for (int64_t d = 0; d < dim; ++d) {
          a[d] += merged[d] * merged[d];
          w[d] -= lr * merged[d] / std::sqrt(a[d] + eps);
        }
      } else {
        for (int64_t d = 0; d < dim; ++d) w[d] -= lr * merged[d];
      }
    }
    i = j;
  }
}

}  // extern "C"
