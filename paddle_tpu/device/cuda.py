"""paddle.device.cuda parity surface. There is no CUDA on TPU; queries
report zero devices instead of raising so device-agnostic user code
(`if paddle.device.cuda.device_count(): ...`) keeps working."""
from __future__ import annotations

__all__ = ["device_count", "synchronize", "empty_cache",
           "max_memory_allocated", "max_memory_reserved",
           "memory_allocated", "memory_reserved", "Stream", "Event"]


def device_count():
    return 0


def synchronize(device=None):
    from paddle_tpu.device import synchronize as sync
    return sync(device)


def empty_cache():
    return None


def max_memory_allocated(device=None):
    return 0


def max_memory_reserved(device=None):
    return 0


def memory_allocated(device=None):
    return 0


def memory_reserved(device=None):
    return 0


class Stream:
    def __init__(self, *a, **kw):
        raise RuntimeError("CUDA streams do not exist on the TPU backend; "
                           "XLA schedules compute/collective streams itself")


class Event(Stream):
    pass
