"""paddle.device parity namespace (reference: python/paddle/device/).

The reference hosts CUDA stream/event control here; the TPU analogue of
"synchronize" is draining the async XLA dispatch queue.
"""
from __future__ import annotations

from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    get_device,
    device_count,
    is_compiled_with_cuda,
    is_compiled_with_npu,
    is_compiled_with_rocm,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)

from . import cuda  # noqa: F401


class IPUPlace:
    def __init__(self, *a):
        raise RuntimeError("IPU is not a TPU-system device; use TPUPlace")


class MLUPlace:
    def __init__(self, *a):
        raise RuntimeError("MLU is not a TPU-system device; use TPUPlace")


def get_cudnn_version():
    return None


def is_compiled_with_cinn():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_mlu():
    return False

__all__ = [
    "get_device", "set_device", "device_count", "synchronize",
    "is_compiled_with_cuda", "is_compiled_with_rocm",
    "is_compiled_with_xpu", "is_compiled_with_npu",
    "is_compiled_with_tpu", "get_all_device_type",
    "get_all_custom_device_type", "get_available_device",
    "get_available_custom_device",
    "CPUPlace", "CUDAPlace", "CUDAPinnedPlace", "TPUPlace", "XPUPlace",
    "IPUPlace", "MLUPlace", "get_cudnn_version",
    "is_compiled_with_cinn", "is_compiled_with_ipu",
    "is_compiled_with_mlu",
]


def synchronize(device=None):
    """Block until all queued device work completes (the reference's
    cuda.synchronize; XLA's dispatch is async the same way)."""
    import jax
    try:
        jax.block_until_ready(
            jax.device_put(0, jax.devices()[0] if device is None else device))
    except Exception:
        pass


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device()
            if not d.startswith(("cpu", "gpu"))]
