"""paddle.text parity namespace (viterbi_decode + datasets).

Reference: python/paddle/text/viterbi_decode.py (viterbi_decode :24,
ViterbiDecoder :100); numeric semantics follow the phi kernel
(paddle/phi/kernels/cpu/viterbi_decode_kernel.cc): with
include_bos_eos_tag, transitions' last row is the start->tag score and
the second-to-last row the tag->stop score.

TPU-native: the per-timestep max-product recursion is one lax.scan over
time (statically shaped, jittable); the backtrace is a second scan over
the stored argmax history. The reference's hand-rolled buffer arithmetic
(masked updates for ragged lengths) becomes jnp.where masking.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets",
           "Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05",
           "Conll05st", "WMT14", "WMT16"]

from paddle_tpu.text import datasets  # noqa: F401,E402
# dataset classes at the reference path (python/paddle/text/__init__.py
# re-exports paddle.text.Imdb etc. directly)
from paddle_tpu.text.datasets import (  # noqa: F401,E402
    Conll05,
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)


def _t(x):
    import numpy as np
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(np.asarray(x)))


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence under a linear-chain CRF.

    potentials: [B, T, n] unary scores; transition_params: [n, n];
    lengths: [B] int. Returns (scores [B], path [B, max(lengths)]);
    positions at or beyond a sequence's length are 0.
    """

    def fn(emit, trans, lens):
        B, T, n = emit.shape
        lens = lens.astype(jnp.int32)
        start = trans[-1] if include_bos_eos_tag else jnp.zeros((n,))
        stop = trans[-2] if include_bos_eos_tag else jnp.zeros((n,))

        alpha0 = emit[:, 0] + start[None, :]
        if include_bos_eos_tag:
            alpha0 = alpha0 + jnp.where((lens == 1)[:, None],
                                        stop[None, :], 0.0)

        def step(alpha, t):
            cand = alpha[:, :, None] + trans[None, :, :]   # [B, i, j]
            hist = jnp.argmax(cand, axis=1)                # [B, j]
            nxt = jnp.max(cand, axis=1) + emit[:, t]
            if include_bos_eos_tag:
                nxt = nxt + jnp.where((lens == t + 1)[:, None],
                                      stop[None, :], 0.0)
            active = (t < lens)[:, None]
            return jnp.where(active, nxt, alpha), hist

        alpha, hists = lax.scan(step, alpha0, jnp.arange(1, T))
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

        # backtrace: walk hists [T-1, B, n] in reverse; a position t holds
        # the best tag at time t; inactive (t >= len) positions emit 0
        def back(tag, t):
            hist = hists[t - 1]                              # [B, n]
            prev = jnp.take_along_axis(hist, tag[:, None],
                                       axis=1)[:, 0].astype(jnp.int32)
            # only walk back while t < len (tag at time t is defined)
            newtag = jnp.where(t < lens, prev, tag)
            out = jnp.where(t < lens, tag, 0)
            return newtag, out

        tag_final, outs = lax.scan(back, last, jnp.arange(T - 1, 0, -1))
        # outs[k] is the emitted tag at time T-1-k; prepend time 0
        path = jnp.concatenate([tag_final[None, :], outs[::-1]], axis=0)
        path = jnp.swapaxes(path, 0, 1)                      # [B, T]
        max_len = T
        return scores, path[:, :max_len]

    scores, path = apply(fn, _t(potentials), _t(transition_params),
                         _t(lengths))
    # trim to the batch's longest sequence (reference: [B, max(lengths)])
    import numpy as np
    ln = np.asarray(jax.device_get(_t(lengths)._value))
    max_len = int(ln.max()) if ln.size else 0
    return scores, Tensor(path._value[:, :max_len].astype(jnp.int64))


class ViterbiDecoder(Layer):
    """Reference text/viterbi_decode.py:100."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
