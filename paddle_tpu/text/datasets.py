"""paddle.text.datasets parity (reference: python/paddle/text/datasets/).

Zero-egress environment: the reference downloads corpora; here each
dataset synthesizes deterministic procedural data with the reference's
item shapes/dtypes, so user pipelines (tokenized docs + labels, n-gram
tuples, rating tuples, regression rows) run unchanged.  Statistical
structure is injected (class-conditional token distributions, user/item
biases) so models measurably learn, mirroring vision/datasets.py.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.io import Dataset

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05",
           "Conll05st",
           "WMT14", "WMT16"]


class Imdb(Dataset):
    """Sentiment-labelled token-id documents (reference imdb.py:30)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode in ("train", "test")
        rng = np.random.default_rng(42 if mode == "train" else 43)
        n = 512 if mode == "train" else 128
        self.word_idx = {f"w{i}": i for i in range(cutoff)}
        self.docs, self.labels = [], []
        for i in range(n):
            label = i % 2
            length = int(rng.integers(16, 64))
            # sentiment-dependent token bias makes the task learnable
            base = rng.integers(0, cutoff // 2, length)
            shift = (cutoff // 2) * label
            doc = (base + shift * (rng.random(length) < 0.7)).astype(np.int64)
            self.docs.append(doc % cutoff)
            self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram tuples (reference imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        assert data_type in ("NGRAM", "SEQ")
        rng = np.random.default_rng(7 if mode == "train" else 8)
        vocab = 200
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        n = 1024 if mode == "train" else 256
        self.data = []
        stream = rng.integers(0, vocab, n + window_size)
        # Markov-ish structure: next token correlates with previous
        for i in range(1, len(stream)):
            stream[i] = (stream[i - 1] + stream[i]) % vocab
        if data_type == "NGRAM":
            for i in range(n):
                self.data.append(tuple(stream[i:i + window_size]))
        else:
            for i in range(n // 8):
                seq = stream[i * 8:(i + 1) * 8]
                self.data.append((seq[:-1], seq[1:]))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """(user_id, gender, age, job, movie_id, category, title, rating)
    tuples (reference movielens.py:232)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.default_rng(rand_seed)
        n_users, n_movies = 100, 200
        user_bias = rng.normal(0, 1, n_users)
        movie_bias = rng.normal(0, 1, n_movies)
        rows = []
        for _ in range(2000):
            u = int(rng.integers(0, n_users))
            m = int(rng.integers(0, n_movies))
            score = 3.0 + user_bias[u] + movie_bias[m] + rng.normal(0, 0.3)
            rows.append((
                np.array([u]), np.array([int(rng.integers(0, 2))]),
                np.array([int(rng.integers(1, 7))]),
                np.array([int(rng.integers(0, 21))]),
                np.array([m]),
                rng.integers(0, 18, 3).astype(np.int64),
                rng.integers(0, 5000, 4).astype(np.int64),
                np.array([float(np.clip(round(score), 1, 5))],
                         np.float32),
            ))
        cut = int(len(rows) * (1 - test_ratio))
        self.data = rows[:cut] if mode == "train" else rows[cut:]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """13-feature housing regression rows (reference uci_housing.py)."""

    N_FEAT = 13

    def __init__(self, data_file=None, mode="train", download=True):
        rng = np.random.default_rng(13 if mode == "train" else 14)
        n = 404 if mode == "train" else 102
        x = rng.normal(0, 1, (n, self.N_FEAT))
        w = rng.normal(0, 1, self.N_FEAT)
        y = x @ w + rng.normal(0, 0.1, n)
        self.data = np.concatenate([x, y[:, None]], axis=1)
        self.dtype = "float32"

    def __getitem__(self, idx):
        row = self.data[idx]
        return (np.array(row[:-1]).astype(self.dtype),
                np.array(row[-1:]).astype(self.dtype))

    def __len__(self):
        return len(self.data)


class Conll05(Dataset):
    """SRL tuples: (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred,
    mark, label) id sequences (reference conll05.py)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 mode="train", download=True):
        rng = np.random.default_rng(5 if mode == "train" else 6)
        self.word_dict = {f"w{i}": i for i in range(800)}
        self.predicate_dict = {f"v{i}": i for i in range(60)}
        self.label_dict = {f"l{i}": i for i in range(20)}
        n = 256 if mode == "train" else 64
        self.data = []
        for _ in range(n):
            length = int(rng.integers(5, 30))
            words = rng.integers(0, 800, length).astype(np.int64)
            ctx = [np.roll(words, s) for s in (2, 1, 0, -1, -2)]
            pred = np.full(length, rng.integers(0, 60), np.int64)
            mark = (rng.random(length) < 0.2).astype(np.int64)
            label = rng.integers(0, 20, length).astype(np.int64)
            self.data.append((words, *ctx, pred, mark, label))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """(src_ids, trg_ids, trg_ids_next) translation triples
    (reference wmt14.py)."""

    DICT_SIZE = 1000
    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        self.dict_size = self.DICT_SIZE if dict_size < 0 else dict_size
        rng = np.random.default_rng(140 if mode == "train" else 141)
        n = 512 if mode == "train" else 128
        self.data = []
        for _ in range(n):
            length = int(rng.integers(4, 20))
            src = rng.integers(3, self.dict_size, length).astype(np.int64)
            # target: deterministic per-token mapping + BOS/EOS framing
            trg_core = (src * 7 + 11) % self.dict_size
            trg = np.concatenate([[self.BOS], trg_core])
            trg_next = np.concatenate([trg_core, [self.EOS]])
            self.data.append((src, trg.astype(np.int64),
                              trg_next.astype(np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)

    def get_dict(self, lang="en", reverse=False):
        d = {f"tok{i}": i for i in range(self.dict_size)}
        return {v: k for k, v in d.items()} if reverse else d


class WMT16(WMT14):
    """Same triple layout, separate vocab handles (reference wmt16.py)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        super().__init__(mode=mode,
                         dict_size=max(src_dict_size, trg_dict_size))


# the reference exports this dataset as Conll05st (text/datasets/conll05.py)
Conll05st = Conll05
