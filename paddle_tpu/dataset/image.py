"""paddle.dataset.image (reference: python/paddle/dataset/image.py):
numpy/PIL image helpers for the fluid-era pipelines (the reference uses
cv2; PIL is what this image bundles — same semantics, HWC uint8 in,
float CHW out of simple_transform)."""
from __future__ import annotations

import numpy as np

__all__ = ["load_image", "load_image_bytes", "resize_short", "to_chw",
           "center_crop", "random_crop", "left_right_flip",
           "simple_transform", "load_and_transform"]


def _pil():
    from PIL import Image
    return Image


def load_image_bytes(bytes_, is_color=True):
    import io
    img = _pil().open(io.BytesIO(bytes_))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(file, is_color=True):
    img = _pil().open(file)
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def resize_short(im, size):
    """Scale so the SHORT edge equals `size` (aspect preserved)."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, int(round(w * size / h))
    else:
        nh, nw = int(round(h * size / w)), size
    img = _pil().fromarray(im)
    return np.asarray(img.resize((nw, nh), _pil().BILINEAR))


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short + (random crop + flip | center crop) + CHW float
    (reference image.py:329)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
