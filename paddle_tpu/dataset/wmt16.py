"""paddle.dataset.wmt16 (reference: python/paddle/dataset/wmt16.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.text.datasets import WMT16
    return _mk(WMT16, "train", **kw)


def test(**kw):
    from paddle_tpu.text.datasets import WMT16
    return _mk(WMT16, "test", **kw)

