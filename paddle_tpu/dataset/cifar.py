"""paddle.dataset.cifar (reference: python/paddle/dataset/cifar.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train10(**kw):
    from paddle_tpu.vision.datasets import Cifar10
    return _mk(Cifar10, "train", **kw)


def test10(**kw):
    from paddle_tpu.vision.datasets import Cifar10
    return _mk(Cifar10, "test", **kw)


def train100(**kw):
    from paddle_tpu.vision.datasets import Cifar100
    return _mk(Cifar100, "train", **kw)


def test100(**kw):
    from paddle_tpu.vision.datasets import Cifar100
    return _mk(Cifar100, "test", **kw)

