"""paddle.dataset.voc2012 (reference: python/paddle/dataset/voc2012.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.vision.datasets import VOC2012
    return _mk(VOC2012, "train", **kw)


def test(**kw):
    from paddle_tpu.vision.datasets import VOC2012
    return _mk(VOC2012, "test", **kw)


def val(**kw):
    from paddle_tpu.vision.datasets import VOC2012
    return _mk(VOC2012, "test", **kw)

