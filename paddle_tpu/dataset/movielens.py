"""paddle.dataset.movielens (reference: python/paddle/dataset/movielens.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.text.datasets import Movielens
    return _mk(Movielens, "train", **kw)


def test(**kw):
    from paddle_tpu.text.datasets import Movielens
    return _mk(Movielens, "test", **kw)

