"""paddle.dataset.conll05 (reference: python/paddle/dataset/conll05.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def test(**kw):
    from paddle_tpu.text.datasets import Conll05
    return _mk(Conll05, "test", **kw)

