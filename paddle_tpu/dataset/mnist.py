"""paddle.dataset.mnist (reference: python/paddle/dataset/mnist.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.vision.datasets import MNIST
    return _mk(MNIST, "train", **kw)


def test(**kw):
    from paddle_tpu.vision.datasets import MNIST
    return _mk(MNIST, "test", **kw)

