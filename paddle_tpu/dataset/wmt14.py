"""paddle.dataset.wmt14 (reference: python/paddle/dataset/wmt14.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.text.datasets import WMT14
    return _mk(WMT14, "train", **kw)


def test(**kw):
    from paddle_tpu.text.datasets import WMT14
    return _mk(WMT14, "test", **kw)

