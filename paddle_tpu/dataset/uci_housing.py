"""paddle.dataset.uci_housing (reference: python/paddle/dataset/uci_housing.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.text.datasets import UCIHousing
    return _mk(UCIHousing, "train", **kw)


def test(**kw):
    from paddle_tpu.text.datasets import UCIHousing
    return _mk(UCIHousing, "test", **kw)

