"""paddle.dataset — the fluid-era reader-factory surface (reference:
python/paddle/dataset/). Each submodule exposes train()/test() readers
(zero-arg callables yielding samples) over the same offline-synthesized
datasets the class-style paddle.io datasets use; `paddle.reader`
decorators compose them. Kept for migrating legacy pipelines."""
from paddle_tpu.dataset import (  # noqa: F401
    cifar,
    common,
    conll05,
    flowers,
    image,
    imdb,
    imikolov,
    mnist,
    movielens,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)
