"""paddle.dataset.flowers (reference: python/paddle/dataset/flowers.py):
reader factories over the offline paddle_tpu datasets (shared iteration
logic: paddle_tpu.dataset.common.make_reader)."""
from __future__ import annotations

from paddle_tpu.dataset.common import make_reader as _mk


def train(**kw):
    from paddle_tpu.vision.datasets import Flowers
    return _mk(Flowers, "train", **kw)


def test(**kw):
    from paddle_tpu.vision.datasets import Flowers
    return _mk(Flowers, "test", **kw)


def valid(**kw):
    from paddle_tpu.vision.datasets import Flowers
    return _mk(Flowers, "test", **kw)

