"""Reference dataset/common.py: download cache helpers. Zero-egress
build: DATA_HOME exists for path compatibility; download() of a file
already on disk passes through, anything else raises (no network)."""
from __future__ import annotations

import os

DATA_HOME = os.path.expanduser("~/.cache/paddle/dataset")


def md5file(fname):
    import hashlib
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    os.makedirs(os.path.join(DATA_HOME, module_name), exist_ok=True)
    path = os.path.join(DATA_HOME, module_name,
                        save_name or url.split("/")[-1])
    if os.path.exists(path):
        if md5sum and md5file(path) != md5sum:
            raise RuntimeError(
                f"paddle.dataset.common.download: {path} exists but its "
                f"md5 does not match {md5sum} (corrupt or truncated "
                f"pre-placed file)")
        return path
    raise RuntimeError(
        f"paddle.dataset.common.download: zero-egress build cannot fetch "
        f"{url}; place the file at {path} or use the paddle_tpu offline "
        f"datasets (paddle.vision.datasets / paddle.text)")


def make_reader(dataset_cls, mode, **kw):
    """Shared reader factory: instantiate the paddle_tpu dataset class
    and yield its samples as tuples (the one copy of the iteration/
    normalization logic every paddle.dataset submodule delegates to)."""
    def impl():
        ds = dataset_cls(mode=mode, **kw)
        for i in range(len(ds)):
            item = ds[i]
            yield tuple(item) if isinstance(item, (list, tuple)) else item

    return impl
