"""Autograd public API. Reference: python/paddle/autograd/__init__.py."""
from __future__ import annotations

import jax

from paddle_tpu.core.engine import (  # noqa: F401
    backward as _engine_backward,
    enable_grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.autograd.functional import (  # noqa: F401
    Hessian,
    Jacobian,
    jvp,
    vjp,
)
from paddle_tpu.autograd.saved_tensors_hooks import (  # noqa: F401
    saved_tensors_hooks,
)


def backward(tensors, grad_tensors=None, retain_graph=False):
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    for idx, (t, g) in enumerate(zip(tensors, grad_tensors)):
        # keep shared nodes alive for the remaining outputs of THIS call
        _engine_backward(
            t, g, retain_graph=retain_graph or idx < len(tensors) - 1)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — compute grads of outputs wrt inputs without touching .grad."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    # grads flow into a SINK, never into .grad — paddle.grad must leave
    # every leaf's .grad untouched (a later loss.backward() would
    # otherwise silently accumulate on top of stale values). Requested
    # INTERMEDIATES are captured at the moment their cotangent
    # completes in the walk (wanted_uids).
    retain = True if retain_graph is None else retain_graph
    sink = {}
    wanted = {i._uid for i in inputs}
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    for idx, (t, g) in enumerate(zip(outputs, grad_outputs)):
        # the walk runs once per output; earlier passes must keep the
        # graph alive for later outputs that share nodes with them, even
        # under explicit retain_graph=False (reference paddle seeds all
        # outputs into a single engine pass)
        keep = retain or idx < len(outputs) - 1
        _engine_backward(t, g,
                         retain_graph=True if create_graph else keep,
                         differentiable=create_graph, grad_sink=sink,
                         wanted_uids=wanted)
    grads = []
    for i in inputs:
        g = sink.get(i._uid)
        if g is not None and not isinstance(g, Tensor):
            g = Tensor(g, stop_gradient=True)
        if g is None and not allow_unused:
            from paddle_tpu.tensor.creation import zeros_like
            g = zeros_like(i)
        grads.append(g)
    return grads


class _CallableTuple(tuple):
    """Tuple that can also be CALLED to return itself — bridges paddle's
    ctx.saved_tensor() method spelling and property-style unpacking."""

    def __call__(self):
        return tuple(self)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._saved_hooks = None
        self._packed_mask = ()

    def save_for_backward(self, *tensors):
        from paddle_tpu.autograd.saved_tensors_hooks import current_hooks
        hooks = current_hooks()
        if hooks is not None:
            pack, _ = hooks
            # pack only Tensors; non-tensor metadata passes through and
            # must not be run through unpack at backward time
            self._saved = tuple(pack(t) if isinstance(t, Tensor) else t
                                for t in tensors)
            self._packed_mask = tuple(isinstance(t, Tensor) for t in tensors)
            self._saved_hooks = hooks
        else:
            self._saved = tensors

    def _unpacked(self):
        if self._saved_hooks is None:
            return self._saved
        _, unpack = self._saved_hooks
        out = []
        for p, was_packed in zip(self._saved, self._packed_mask):
            if not was_packed:
                out.append(p)
                continue
            u = unpack(p)
            out.append(u if isinstance(u, Tensor) else Tensor(u))
        return tuple(out)

    @property
    def saved_tensor(self):
        # reference API: ctx.saved_tensor() is a METHOD; some earlier
        # code here unpacked it as a property. _CallableTuple supports
        # both spellings.
        return _CallableTuple(self._unpacked())

    def saved_tensors(self):
        return self._unpacked()


class PyLayer:
    """Custom op with user fwd/bwd. Reference: python/paddle/autograd/py_layer.py."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from paddle_tpu.core import engine
        ctx = PyLayerContext()
        out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = (out,) if single else tuple(out)
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        if engine.is_grad_enabled() and any(not t.stop_gradient for t in in_tensors):
            def pullback(cots):
                if single:
                    cots = (cots,)
                gts = cls.backward(ctx, *[Tensor(c, stop_gradient=True) for c in (
                    cots if isinstance(cots, tuple) else (cots,))])
                if isinstance(gts, Tensor):
                    gts = (gts,)
                return tuple(None if g is None else g._value for g in gts)
            new_outs = []
            for o in outs:
                t = Tensor(o._value, stop_gradient=False)
                new_outs.append(t)
            node = engine.Node(in_tensors, tuple(new_outs), pullback, name=cls.__name__)
            for t in new_outs:
                t._node = node
            outs = tuple(new_outs)
        return outs[0] if single else outs
