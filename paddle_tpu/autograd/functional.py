"""Functional autodiff: vjp / jvp / Jacobian / Hessian.

Reference: python/paddle/incubate/autograd/functional.py (vjp :22,
jvp :80, Jacobian :245, Hessian further down), also surfaced as
paddle.autograd.{vjp,jvp,Jacobian,Hessian}.

The reference builds these out of double-backward tricks over the fluid
autograd graph; here each is a direct jax transform over a purified view
of the user function (the same Tensor->value lifting `to_static` uses),
so jvp is true forward-mode — not the reference's double-VJP emulation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _values(xs):
    return [x._value if isinstance(x, Tensor) else jnp.asarray(x)
            for x in xs]


def _purify(func, n):
    """Wrap a Tensor->Tensor(s) function as a jax-value function (tape ops
    trace through jax transparently — same mechanism as jit.to_static)."""

    def fn(*vals):
        outs = func(*[Tensor(v) for v in vals])
        if isinstance(outs, (list, tuple)):
            return tuple(o._value for o in outs)
        return outs._value

    return fn


def _rewrap(vals):
    if isinstance(vals, tuple):
        out = tuple(Tensor(v) for v in vals)
        return out if len(out) != 1 else out[0]
    return Tensor(vals)


def vjp(func, xs, v=None):
    """Vector-Jacobian product: returns (func(xs), vjp) where vjp is the
    cotangent pullback of `v` (defaults to ones like the output)."""
    xs = _as_list(xs)
    fn = _purify(func, len(xs))
    vals = _values(xs)
    out, pull = jax.vjp(fn, *vals)
    if v is None:
        seed = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        vv = _values(_as_list(v))
        seed = tuple(vv) if isinstance(out, tuple) else vv[0]
    grads = pull(seed)
    grads = tuple(Tensor(g) for g in grads)
    return _rewrap(out), grads if len(grads) != 1 else grads[0]


def jvp(func, xs, v=None):
    """Jacobian-vector product (true forward-mode on TPU)."""
    xs = _as_list(xs)
    fn = _purify(func, len(xs))
    vals = _values(xs)
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        tangents = _values(_as_list(v))
    out, tang = jax.jvp(fn, vals, tangents)
    return _rewrap(out), _rewrap(tang)


class Jacobian:
    """Lazy Jacobian matrix of func at xs (reference functional.py:245).

    For single input x [N] and output [M], `J[:]` is [M, N]; `J[i]` rows
    index the output dimension.  `is_batched=True` treats axis 0 of
    inputs/outputs as a batch dimension, giving [B, M, N].
    """

    def __init__(self, func, xs, is_batched=False):
        xs = _as_list(xs)
        fn = _purify(func, len(xs))
        vals = _values(xs)

        def single_out(*a):
            out = fn(*a)
            if isinstance(out, tuple):
                raise TypeError(
                    "Jacobian expects func returning a single Tensor "
                    "(reference functional.Jacobian contract); got a tuple")
            return out

        argnums = tuple(range(len(vals)))
        if is_batched:
            jac = jax.vmap(jax.jacrev(single_out, argnums=argnums))(*vals)
        else:
            jac = jax.jacrev(single_out, argnums=argnums)(*vals)
        jac = jac[0] if len(vals) == 1 else jac
        self._jac = Tensor(jnp.asarray(jac)) if not isinstance(jac, tuple) \
            else tuple(Tensor(jnp.asarray(j)) for j in jac)

    def __getitem__(self, idx):
        return self._jac[idx]

    @property
    def shape(self):
        return self._jac.shape

    def numpy(self):
        return self._jac.numpy()


class Hessian:
    """Hessian of a scalar-output func at xs."""

    def __init__(self, func, xs, is_batched=False):
        xs = _as_list(xs)
        fn = _purify(func, len(xs))
        vals = _values(xs)

        def scalar_fn(*a):
            out = fn(*a)
            out = out[0] if isinstance(out, tuple) else out
            return jnp.reshape(out, ())

        argnums = tuple(range(len(vals)))
        if is_batched:
            hess = jax.vmap(jax.hessian(scalar_fn, argnums=argnums))(*vals)
        else:
            hess = jax.hessian(scalar_fn, argnums=argnums)(*vals)
        if len(vals) == 1:
            self._hess = Tensor(jnp.asarray(hess[0][0]))
        else:
            # full block structure: tuple-of-tuples of Tensors
            self._hess = tuple(
                tuple(Tensor(jnp.asarray(b)) for b in row) for row in hess)

    def __getitem__(self, idx):
        return self._hess[idx]

    @property
    def shape(self):
        return self._hess.shape

    def numpy(self):
        return self._hess.numpy()
