"""saved_tensors_hooks — intercept what the autograd engine saves for backward.

Reference: python/paddle/autograd/saved_tensors_hooks.py:20 (pack_hook runs
when an op saves a tensor for its grad computation; unpack_hook runs when the
backward pass consumes it — the hook point for activation offload /
compression).

TPU-native design: in this engine the "tensors saved for backward" are the
residuals captured by the eager ``jax.vjp`` closure of each recorded op
(core/dispatch.py:apply). While a hook pair is active, ``apply`` does NOT
retain that closure: it packs the op's differentiable *input* values through
``pack_hook`` (e.g. ``lambda t: t.numpy()`` moves them to host RAM) and the
pullback re-runs ``jax.vjp`` from the unpacked inputs at backward time —
op-granular rematerialization with user-controlled storage, which is exactly
the offload/compression use case. The tape also holds those inputs WEAKLY
(engine._InRef): once user code drops an offloaded activation, the packed
form is the only copy the graph retains and the device buffer is freed —
cotangent routing survives collection because node identity is recorded as
(uid, version) snapshots, not live objects. ``PyLayer.save_for_backward`` /
``ctx.saved_tensor`` route through the same hooks, matching the reference's
PyLayer contract. (Under ``to_static`` the whole step is one XLA program;
memory there is managed with ``recompute``/remat, not eager hooks.)
"""
from __future__ import annotations

import threading


class _HookState(threading.local):
    def __init__(self):
        self.stack = []


_state = _HookState()


def current_hooks():
    """The innermost active (pack_hook, unpack_hook) pair, or None."""
    return _state.stack[-1] if _state.stack else None


class saved_tensors_hooks:
    """Context manager registering a pack/unpack hook pair.

    Example (offload eager activations to host RAM)::

        def pack(t):
            return t.numpy()            # device -> host copy

        def unpack(packed):
            return paddle_tpu.to_tensor(packed)

        with paddle_tpu.autograd.saved_tensors_hooks(pack, unpack):
            y = model(x)
        y.backward()                     # unpack runs here
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _state.stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _state.stack.pop()
        return False
