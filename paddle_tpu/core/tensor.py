"""paddle_tpu.Tensor — a paddle-compatible eager tensor over ``jax.Array``.

Reference parity: paddle's eager Tensor (paddle/fluid/pybind/eager_method.cc,
python/paddle/fluid/dygraph/varbase_patch_methods.py). TPU-first design:
values are immutable jax.Arrays; "in-place" ops rebind ``_value`` and bump a
version counter (used by the autograd engine for correctness). Every op flows
through :func:`apply`, which optionally records a ``jax.vjp`` pullback Node so
``loss.backward()`` works in eager mode and — because the same code path runs
on JAX tracers — whole train steps compile to one XLA program under
``paddle_tpu.jit.to_static``.
"""
from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import engine
from paddle_tpu.core import dtype as dtypes
from paddle_tpu.core.device import CPUPlace, Place, TPUPlace, _default_place

_tree = jax.tree_util


def _is_diff_dtype(dt) -> bool:
    return jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating)


def sync_array(value):
    """Reliably wait for ``value``'s computation to finish.

    On the tunneled TPU platform ("axon") ``block_until_ready`` can return
    before execution completes; a device→host fetch of one element is the
    only dependable barrier there. Fetching a single scalar keeps the
    transfer negligible while still forcing the producing computation.
    """
    value.block_until_ready()
    try:
        platform = next(iter(value.devices())).platform
    except (AttributeError, StopIteration):  # tracers / committed-less vals
        return value
    if value.size and platform != "cpu":
        # index one element (not ravel — that would reshard the whole
        # array when it's distributed) to force the producing computation.
        # Deliberately NOT under a blanket except: a failing fetch here is
        # a real execution failure and must surface, not be masked.
        jax.device_get(value[(0,) * value.ndim])
    return value


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "name",
        "persistable",
        "_node",
        "_version",
        "_uid",
        "__weakref__",
        "__dict__",
    )

    _tensor_id = [0]

    def __init__(self, value, stop_gradient=True, name=None, place=None):
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, jax.Array) and not isinstance(value, jax.core.Tracer):
            value = jnp.asarray(value)
        self._value = value
        self._init_meta(stop_gradient, name)

    def _init_meta(self, stop_gradient, name=None):
        """Non-storage field init, shared with subclasses that manage
        their own storage (SparseCooTensor's lazy dense mirror)."""
        self.stop_gradient = bool(stop_gradient)
        self.grad = None
        Tensor._tensor_id[0] += 1
        self._uid = Tensor._tensor_id[0]   # never reused (id() can be)
        self.name = name or f"tensor_{Tensor._tensor_id[0]}"
        self.persistable = False
        self._node = None
        self._version = 0

    # ---- metadata ----
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dim(self):
        return self._value.ndim

    @property
    def rank(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype) if self._value.dtype != dtypes.bfloat16 else dtypes.bfloat16

    @property
    def place(self):
        try:
            dev = list(self._value.devices())[0]
            return CPUPlace(dev.id) if dev.platform == "cpu" else TPUPlace(dev.id)
        except Exception:
            return _default_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from paddle_tpu.tensor.linalg import t
        return t(self)

    def dims(self):
        return self.shape

    # ---- conversion ----
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return np.asarray(self._value).item(*args)
        return np.asarray(self._value).item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dt):
        from paddle_tpu.core.dispatch import apply
        dt = dtypes.convert_dtype(dt)
        return apply(lambda v: v.astype(dt), self)

    def cast(self, dt):
        return self.astype(dt)

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from paddle_tpu.core.dispatch import apply
        return apply(lambda v: v + 0 if v.dtype != np.dtype("bool") else v, self)

    def cpu(self):
        return Tensor(jax.device_put(self._value, CPUPlace(0).jax_device),
                      stop_gradient=self.stop_gradient)

    def cuda(self, device_id=0):
        return Tensor(jax.device_put(self._value, TPUPlace(device_id).jax_device),
                      stop_gradient=self.stop_gradient)

    tpu = cuda

    def pin_memory(self):
        return self

    def to(self, *args, **kwargs):
        dt = kwargs.get("dtype")
        device = kwargs.get("device")
        for a in args:
            if isinstance(a, str) and a.split(":")[0] in ("cpu", "gpu", "tpu", "cuda"):
                device = a
            elif isinstance(a, Place):
                device = a
            else:
                dt = a
        out = self
        if dt is not None:
            out = out.astype(dt)
        if device is not None:
            if isinstance(device, str):
                from paddle_tpu.core.device import set_device
                place = CPUPlace(0) if device.startswith("cpu") else TPUPlace(0)
            else:
                place = device
            out = Tensor(jax.device_put(out._value, place.jax_device),
                         stop_gradient=out.stop_gradient)
        return out

    def block_until_ready(self):
        sync_array(self._value)
        return self

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph=False):
        engine.backward(self, grad_tensor, retain_graph)

    def _accumulate_grad(self, g):
        for h in self.__dict__.get("_grad_hooks", ()):
            r = h(Tensor(g, stop_gradient=True))
            if r is not None:
                g = r._value if isinstance(r, Tensor) else jnp.asarray(r)
        if self.grad is None:
            self.grad = Tensor(g, stop_gradient=True, name=self.name + "@GRAD")
        else:
            self.grad._value = self.grad._value + g

    def clear_grad(self):
        from paddle_tpu.jit.api import note_grad_cleared
        note_grad_cleared(self._uid)
        self.grad = None

    clear_gradient = clear_grad

    def zero_grad(self):
        if self.grad is not None:
            self.grad._value = jnp.zeros_like(self.grad._value)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    def register_hook(self, hook):
        """Grad hook applied when this (leaf) tensor's grad is accumulated."""
        hooks = self.__dict__.setdefault("_grad_hooks", [])
        hooks.append(hook)
        return _HookHandle(self, hook)

    # ---- in-place machinery ----
    def _inplace_assign(self, new_tensor):
        """Adopt new value + node, bump version (in-place op semantics)."""
        self._value = new_tensor._value
        self._version += 1
        node = new_tensor._node
        if node is not None:
            node.out_uids = (self._uid,)
            node.out_versions = (self._version,)
            self._node = node
            self.stop_gradient = new_tensor.stop_gradient
        return self

    def _set_value(self, value):
        """Raw rebind (optimizer/buffer updates, under no_grad)."""
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self._version += 1
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch {value.shape} vs {self._value.shape}")
        return self._set_value(value.astype(self._value.dtype))

    def get_tensor(self):
        return self

    # ---- indexing ----
    def _convert_index(self, idx):
        def conv(x):
            if isinstance(x, Tensor):
                return x._value
            return x
        if isinstance(idx, tuple):
            return tuple(conv(i) for i in idx)
        return conv(idx)

    def __getitem__(self, idx):
        from paddle_tpu.core.dispatch import apply
        idx = self._convert_index(idx)
        return apply(lambda v: v[idx], self)

    def __setitem__(self, idx, value):
        from paddle_tpu.core.dispatch import apply
        idx = self._convert_index(idx)

        def fn(v, val):
            val = jnp.asarray(val, dtype=v.dtype) if not hasattr(val, "dtype") else val.astype(v.dtype)
            return v.at[idx].set(val)

        out = apply(fn, self, value)
        self._inplace_assign(out)

    # ---- python protocol ----
    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __index__(self):
        return int(np.asarray(self._value))

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return format(str(self), spec)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        try:
            vals = np.asarray(self._value)
            body = np.array2string(vals, precision=8, separator=", ")
        except Exception:
            body = "<traced>"
        return (
            f"Tensor(shape={self.shape}, dtype={self._value.dtype}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    __str__ = __repr__

    def __dlpack__(self, *a, **kw):
        return self._value.__dlpack__(*a, **kw)


class _HookHandle:
    def __init__(self, tensor, hook):
        self._ref = weakref.ref(tensor)
        self._hook = hook

    def remove(self):
        t = self._ref()
        if t is not None:
            hooks = t.__dict__.get("_grad_hooks", [])
            if self._hook in hooks:
                hooks.remove(self._hook)


class Parameter(Tensor):
    """Trainable tensor (stop_gradient=False), auto-registered for to_static
    state lifting. Reference: python/paddle/fluid/framework.py Parameter."""

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True
        from paddle_tpu.framework.state import register_state_tensor
        register_state_tensor(self)

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v


def register_tensor_method(name, fn=None):
    """Attach a free function from paddle_tpu.tensor.* as a Tensor method."""
    def deco(f):
        setattr(Tensor, name, f)
        return f
    if fn is not None:
        return deco(fn)
    return deco
