"""Bind tensor.* free functions as Tensor methods + operator dunders.

Reference parity: python/paddle/fluid/dygraph/math_op_patch.py /
varbase_patch_methods.py (monkey-patching of the eager Tensor).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu import tensor as T


def _swap(fn):
    def op(self, other):
        return fn(other, self)
    return op


def bind_all():
    # Methods mirroring free functions (paddle patches these onto Tensor).
    method_names = [
        # math
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "mod", "pow", "maximum", "minimum", "fmax", "fmin", "abs", "exp",
        "expm1", "sqrt", "rsqrt", "ceil", "floor", "round", "trunc", "sign",
        "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
        "asinh", "acosh", "atanh", "square", "reciprocal", "erf", "erfinv",
        "log", "log2", "log10", "log1p", "logit", "clip", "sum", "mean",
        "prod", "max", "min", "amax", "amin", "logsumexp", "cumsum",
        "cumprod", "all", "any", "matmul", "mm", "inner", "outer", "kron",
        "lerp", "atan2", "scale", "stanh", "nansum", "nanmean",
        "count_nonzero", "isfinite", "isinf", "isnan", "nan_to_num",
        "heaviside", "diff", "neg", "trace", "diagonal", "digamma", "lgamma",
        "frac", "take", "conj", "angle", "rad2deg", "deg2rad", "gcd",
        "lcm", "add_",
        "subtract_", "multiply_", "clip_", "scale_", "exp_", "sqrt_",
        "rsqrt_", "reciprocal_", "round_", "ceil_", "floor_", "tanh_",
        "fill_", "zero_",
        # logic
        "equal", "not_equal", "greater_than", "greater_equal", "less_than",
        "less_equal", "equal_all", "allclose", "isclose", "logical_and",
        "logical_or", "logical_xor", "logical_not", "bitwise_and",
        "bitwise_or", "bitwise_xor", "bitwise_not",
        # manipulation
        "reshape", "reshape_", "transpose", "moveaxis", "squeeze", "squeeze_",
        "unsqueeze", "unsqueeze_", "flatten", "flatten_", "gather",
        "gather_nd", "scatter", "scatter_", "scatter_nd_add", "tile",
        "expand", "expand_as", "broadcast_to", "flip", "roll", "rot90",
        "unique", "unique_consecutive", "masked_select", "masked_fill",
        "index_select", "index_sample", "index_add", "take_along_axis",
        "put_along_axis", "repeat_interleave", "split", "chunk", "unstack",
        "as_complex", "as_real", "unbind", "tensordot",
        # linalg
        "dot", "bmm", "mv", "t", "cross", "norm", "dist", "cholesky", "det",
        "slogdet", "svd", "qr", "eig", "eigvals", "pinv", "inverse", "solve",
        "matrix_power", "cov", "corrcoef",
        # search
        "argmax", "argmin", "argsort", "sort", "topk", "where", "nonzero",
        "kthvalue", "mode", "bucketize",
        # stat
        "std", "var", "numel", "median", "nanmedian", "quantile",
        "histogram", "bincount",
        # creation
        "tril", "triu", "diag", "diagflat", "zeros_like", "ones_like",
        "full_like",
        # attribute
        "real", "imag",
        # random
        "uniform_", "normal_", "bernoulli_", "exponential_", "multinomial",
    ]
    alias = {"inverse": "inv", "unbind": "unstack"}
    for name in method_names:
        target = alias.get(name, name)
        fn = getattr(T, target, None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)

    # Operator dunders.
    Tensor.__add__ = T.add
    Tensor.__radd__ = _swap(T.add)
    Tensor.__sub__ = T.subtract
    Tensor.__rsub__ = _swap(T.subtract)
    Tensor.__mul__ = T.multiply
    Tensor.__rmul__ = _swap(T.multiply)
    Tensor.__truediv__ = T.divide
    Tensor.__rtruediv__ = _swap(T.divide)
    Tensor.__floordiv__ = T.floor_divide
    Tensor.__rfloordiv__ = _swap(T.floor_divide)
    Tensor.__mod__ = T.remainder
    Tensor.__rmod__ = _swap(T.remainder)
    Tensor.__pow__ = T.pow
    Tensor.__rpow__ = _swap(T.pow)
    Tensor.__matmul__ = T.matmul
    Tensor.__rmatmul__ = _swap(T.matmul)
    Tensor.__neg__ = lambda self: apply(jnp.negative, self)
    Tensor.__pos__ = lambda self: self
    Tensor.__abs__ = T.abs
    Tensor.__invert__ = lambda self: apply(
        lambda v: jnp.logical_not(v) if v.dtype == jnp.bool_ else jnp.bitwise_not(v), self)
    Tensor.__and__ = T.bitwise_and
    Tensor.__or__ = T.bitwise_or
    Tensor.__xor__ = T.bitwise_xor
    Tensor.__eq__ = T.equal
    Tensor.__ne__ = T.not_equal
    Tensor.__lt__ = T.less_than
    Tensor.__le__ = T.less_equal
    Tensor.__gt__ = T.greater_than
    Tensor.__ge__ = T.greater_equal
    Tensor.__hash__ = lambda self: id(self)
