"""Dtype registry, paddle-style dtype names over JAX dtypes.

Reference parity: python/paddle/framework/dtype.py (paddle.float32 etc.).
TPU-first divergence (documented): with jax x64 disabled, float64 maps to
float32 and int64 to int32 — TPUs have no 64-bit ALU path, and paddle's
int64-by-default indices would otherwise double index-bandwidth. The dtype
NAMES remain accepted everywhere for API parity.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
import numpy as np

warnings.filterwarnings(
    "ignore", message=".*requested dtype.*(int64|uint64|float64).*",
    category=UserWarning)

# Canonical dtype objects are numpy dtypes (what jax uses internally).
bool = np.dtype("bool")  # noqa: A001 - paddle exports `paddle.bool`
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = jnp.bfloat16.dtype
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")

_ALIASES = {
    "bool": bool, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "bfloat16": bfloat16,
    "float32": float32, "float64": float64, "complex64": complex64,
    "complex128": complex128,
    # paddle VarDesc-style names
    "FP16": float16, "FP32": float32, "FP64": float64, "BF16": bfloat16,
    "INT8": int8, "INT16": int16, "INT32": int32, "INT64": int64,
    "BOOL": bool, "UINT8": uint8,
}

_default_dtype = [float32]


def convert_dtype(dtype):
    """Normalize str / np.dtype / jnp dtype / python type to np.dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype in _ALIASES:
            return _ALIASES[dtype]
        return np.dtype(dtype)
    if dtype is float:
        return _default_dtype[0]
    if dtype is int:
        return int64
    if dtype is __import__("builtins").bool:
        return np.dtype("bool")
    try:
        return np.dtype(dtype)
    except TypeError:
        return jnp.dtype(dtype)


def set_default_dtype(d):
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(f"set_default_dtype only supports float dtypes, got {d}")
    _default_dtype[0] = d


def get_default_dtype():
    return _default_dtype[0]


def is_floating_dtype(d):
    return jnp.issubdtype(convert_dtype(d), jnp.floating)


def is_integer_dtype(d):
    d = convert_dtype(d)
    return jnp.issubdtype(d, jnp.integer) or d == np.dtype("bool")


def is_complex_dtype(d):
    return jnp.issubdtype(convert_dtype(d), jnp.complexfloating)
