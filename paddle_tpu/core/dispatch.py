"""Op dispatch: every paddle_tpu op funnels through :func:`apply`.

Replaces the reference's per-op C++ kernel dispatch (paddle/phi/core/kernel_*)
with: run the pure-JAX op function eagerly (or on tracers under jit), and — if
any input requires grad — record a ``jax.vjp`` pullback Node for the eager
autograd engine.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core import engine
from paddle_tpu.core.tensor import Tensor, _is_diff_dtype

_tree = jax.tree_util


def _is_tensor(x):
    return isinstance(x, Tensor)


def apply(fn, *args, **kwargs):
    """Execute ``fn`` (a pure function over jnp arrays) on Tensor/array args.

    Tensors anywhere in (nested) args/kwargs are unwrapped; if grad recording
    is active and any differentiable-dtype input has stop_gradient=False, the
    op runs under ``jax.vjp`` and a Node is recorded. Multi-output fns return
    tuples of Tensors.
    """
    leaves, treedef = _tree.tree_flatten((args, kwargs), is_leaf=_is_tensor)
    vals = [l._value if isinstance(l, Tensor) else l for l in leaves]

    diff_idx = []
    if engine.is_grad_enabled():
        for i, l in enumerate(leaves):
            if (
                isinstance(l, Tensor)
                and not l.stop_gradient
                and _is_diff_dtype(l._value.dtype)
            ):
                diff_idx.append(i)

    def run(values):
        a, kw = _tree.tree_unflatten(treedef, values)
        out = fn(*a, **kw)
        return tuple(out) if isinstance(out, list) else out

    if not diff_idx:
        out = run(vals)
        if isinstance(out, tuple):
            return tuple(Tensor(o, stop_gradient=True) for o in out)
        return Tensor(out, stop_gradient=True)

    def closed(diff_vals):
        vs = list(vals)
        for i, v in zip(diff_idx, diff_vals):
            vs[i] = v
        return run(vs)

    from paddle_tpu.autograd.saved_tensors_hooks import current_hooks
    hooks = current_hooks()
    if hooks is not None and any(
            isinstance(v, jax.core.Tracer) for v in vals):
        # saved_tensors_hooks manage EAGER residency; under a trace
        # (to_static / jit) the whole step is one XLA program whose
        # memory is the compiler's / remat's job — and pack hooks that
        # move to host (t.numpy()) cannot act on tracers anyway
        hooks = None
    if hooks is None:
        from paddle_tpu.framework import state as _fstate
        rng_before = _fstate.get_rng_state()
        out_val, pull = jax.vjp(closed, [vals[i] for i in diff_idx])

        def pullback(cot):
            (gs,) = pull(cot)
            return gs
    else:
        # saved_tensors_hooks active: save packed(inputs) instead of the
        # jax.vjp residual closure; recompute the vjp from the unpacked
        # inputs at backward time (see autograd/saved_tensors_hooks.py)
        pack_hook, unpack_hook = hooks
        # stochastic ops draw from the global RNG inside fn; the
        # backward-time recompute must replay the SAME keys (a fresh draw
        # would differentiate a different dropout mask than the forward
        # produced) — snapshot the stream and rewind around the vjp
        from paddle_tpu.framework import state as _fstate
        rng_before = _fstate.get_rng_state()
        out_val = run(vals)
        packed = [pack_hook(Tensor(vals[i], stop_gradient=True))
                  for i in diff_idx]
        # drop the closure's device references to the packed inputs so the
        # packed form (e.g. a host copy) is the only thing the tape retains
        held = list(vals)
        for i in diff_idx:
            held[i] = None

        def closed_late(diff_vals):
            vs = list(held)
            for i, v in zip(diff_idx, diff_vals):
                vs[i] = v
            return run(vs)

        def pullback(cot):
            restored = []
            for p in packed:
                u = unpack_hook(p)
                restored.append(u._value if isinstance(u, Tensor)
                                else jnp.asarray(u))
            cur = _fstate.get_rng_state()
            _fstate.set_rng_state(rng_before)
            try:
                _, pull = jax.vjp(closed_late, restored)
            finally:
                _fstate.set_rng_state(cur)
            (gs,) = pull(cot)
            return gs

    in_tensors = [leaves[i] for i in diff_idx]
    # weak input refs under saved_tensors_hooks: the packed form is then
    # the ONLY thing the tape retains — dropping user refs to an
    # offloaded activation genuinely frees its device buffer
    weak = hooks is not None
    if isinstance(out_val, tuple):
        # the engine hands a SINGLE-output node its cotangent as a bare
        # leaf, but `closed` returned a tuple here — normalize so a
        # 1-element tuple output (e.g. recompute's outs+buffers packing)
        # round-trips through the vjp with matching structure
        inner_pullback = pullback

        def pullback(cot):  # noqa: F811
            return inner_pullback(
                cot if isinstance(cot, tuple) else (cot,))

        outs = tuple(Tensor(o, stop_gradient=False) for o in out_val)
        node = engine.Node(in_tensors, outs, pullback,
                           name=getattr(fn, "__name__", "op"),
                           weak_inputs=weak,
                           fwd=None if hooks is not None else closed,
                           fwd_rng=None if hooks is not None else rng_before,
                           out_is_tuple=True)
        for o in outs:
            o._node = node
        return outs
    out = Tensor(out_val, stop_gradient=False)
    node = engine.Node(in_tensors, (out,), pullback,
                       name=getattr(fn, "__name__", "op"), weak_inputs=weak,
                       fwd=None if hooks is not None else closed,
                       fwd_rng=None if hooks is not None else rng_before)
    out._node = node
    return out


# ------------------------------------------------------------------------
# dtype-promotion metadata — queried by the tracelint jaxpr pass
# (paddle_tpu/analysis/jaxpr_rules.py, rule TL401).  Ops that widen past
# the default float ON PURPOSE (wide accumulations, float64 losses in
# eval-only paths) register their primitive/op name once here and stay
# unflagged everywhere the linter runs.
_WIDE_DTYPE_ALLOWED_OPS: set = set()


def allow_wide_dtype(op_name):
    """Mark `op_name` (a jaxpr primitive or op fn name) as intentionally
    producing float64/complex128; tracelint TL401 skips it."""
    _WIDE_DTYPE_ALLOWED_OPS.add(op_name)
    return op_name


def wide_dtype_allowed_ops():
    return frozenset(_WIDE_DTYPE_ALLOWED_OPS)


def default_float_dtype():
    """The framework-wide default float: float64 only when the user
    enabled jax x64 — then TL401 widening findings are suppressed."""
    return "float64" if jax.config.jax_enable_x64 else "float32"


# Ops that accumulate in a NARROW dtype on purpose (a measured, tested
# tolerance contract — e.g. a stochastic-rounding experiment).  The
# numlint dtype-flow pass (analysis/num_rules.py, rule NL101) skips
# primitives registered here, the same shape as the TL401 wide-dtype
# allowlist above: declare the intent once, stay unflagged everywhere.
_NARROW_ACCUM_ALLOWED_OPS: set = set()


def allow_narrow_accum(op_name):
    """Mark `op_name` (a jaxpr primitive name) as intentionally
    accumulating in a narrow float dtype; numlint NL101 skips it."""
    _NARROW_ACCUM_ALLOWED_OPS.add(op_name)
    return op_name


def narrow_accum_allowed_ops():
    return frozenset(_NARROW_ACCUM_ALLOWED_OPS)


def unwrap(x):
    """Tensor -> jax array (pass through others, recursively on lists/tuples)."""
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(unwrap(v) for v in x)
    return x


def wrap(x, stop_gradient=True):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x), stop_gradient=stop_gradient)
