"""Eager-mode autograd engine: per-op VJP tape.

Reference parity: paddle's C++ autograd engine
(paddle/fluid/eager/backward.cc, grad_node_info) — re-designed for JAX: each
eager op records a `jax.vjp` pullback in a Node; `backward()` walks nodes in
reverse creation order accumulating cotangents. Under `paddle_tpu.jit.to_static`
the same machinery runs on JAX tracers, so the entire forward+backward+update
step fuses into one XLA program — the TPU-native execution model.
"""
from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.observability import profile as _obsprofile

float0 = jax.dtypes.float0


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class no_grad:
    """Context manager / decorator disabling gradient recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = False
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, func):
        import functools

        @functools.wraps(func)
        def wrapper(*a, **kw):
            with no_grad():
                return func(*a, **kw)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = True
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


_node_counter = [0]


class _InRef:
    """One input edge of a Node: identity + topology snapshot.

    Holds the input Tensor STRONGLY by default (pre-existing tape
    semantics: the graph keeps its leaves alive until backward). Under
    `autograd.saved_tensors_hooks` the reference is WEAK — the packed
    form the hook produced is then the only thing the tape retains, so
    offloading an activation to host genuinely releases its device
    buffer once user code drops it. Identity for cotangent routing is
    (uid, version), not id(): uids are never reused, so a collected
    tensor can't alias a later one.
    """

    __slots__ = ("uid", "version", "stop_gradient", "node", "_strong",
                 "_weak")

    def __init__(self, t, weak=False):
        self.uid = t._uid
        self.version = t._version
        self.stop_gradient = t.stop_gradient
        self.node = t._node
        if weak:
            self._strong = None
            self._weak = weakref.ref(t)
        else:
            self._strong = t
            self._weak = None

    def tensor(self):
        return self._strong if self._strong is not None else self._weak()


class Node:
    """One recorded differentiable op."""

    __slots__ = (
        "idx",
        "in_refs",
        "out_uids",
        "out_versions",
        "out_avals",
        "pullback",
        "fwd",
        "fwd_rng",
        "out_is_tuple",
        "name",
        "scope",
    )

    def __init__(self, inputs, out_tensors, pullback, name="",
                 weak_inputs=False, fwd=None, fwd_rng=None,
                 out_is_tuple=False):
        _node_counter[0] += 1
        self.idx = _node_counter[0]
        self.in_refs = tuple(_InRef(t, weak_inputs) for t in inputs)
        self.out_uids = tuple(t._uid for t in out_tensors)
        self.out_versions = tuple(t._version for t in out_tensors)
        self.out_avals = tuple(
            (tuple(t._value.shape), t._value.dtype) for t in out_tensors
        )
        self.pullback = pullback
        self.name = name
        # the layer-scope path active when the op ran forward: backward
        # replays this node's pullback under it, so backward eqns that
        # lose their jax name stack (fresh pull-time traces) still
        # attribute to the owning layer in roofline reports
        self.scope = _obsprofile.current_scope()
        # forward closure over the diff inputs (diff_vals -> outputs):
        # create_graph re-derives the vjp from it so second-order grads
        # see the primal dependence (pullback's residuals are opaque).
        # fwd_rng: the global RNG state the forward ran under — the
        # re-run must replay the SAME stochastic draws (dropout mask)
        self.fwd = fwd
        self.fwd_rng = fwd_rng
        # whether the forward's raw return was a tuple: a fresh
        # jax.vjp(fwd) pullback then expects a TUPLE cotangent even for
        # one output (the stored pullback normalizes this; the
        # create_graph re-derivation must too)
        self.out_is_tuple = out_is_tuple

    @property
    def inputs(self):
        """Live input tensors (compat accessor; None for collected
        weak-held inputs)."""
        return tuple(r.tensor() for r in self.in_refs)


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, dtype=float0)


def backward(root, grad=None, retain_graph=False, differentiable=False,
             grad_sink=None, wanted_uids=None):
    """Run reverse-mode accumulation from `root` tensor into leaf `.grad`s.

    differentiable=True (paddle's create_graph): cotangents flow as
    TAPE-RECORDED tensors — each node's pullback is dispatched through
    apply(), so the produced gradients carry their own graph and can be
    differentiated again (gradient penalty / double backward)."""
    from paddle_tpu.core.tensor import Tensor

    if root._node is None:
        if not root.stop_gradient:
            # leaf with requires-grad: grad of itself
            g = grad if grad is not None else jnp.ones_like(root._value)
            if grad_sink is not None:
                from paddle_tpu.core.tensor import Tensor as _T
                g = g if isinstance(g, _T) else _T(g, stop_gradient=True)
                grad_sink[root._uid] = (grad_sink[root._uid] + g
                                        if root._uid in grad_sink else g)
            else:
                root._accumulate_grad(g)
        return

    if grad is None:
        if root._value.size != 1:
            raise RuntimeError(
                "grad can be implicitly created only for scalar outputs; "
                f"got shape {root._value.shape}"
            )
        grad = jnp.ones_like(root._value)
    elif isinstance(grad, Tensor):
        grad = grad if differentiable else grad._value
    if differentiable:
        return _backward_differentiable(root, grad, retain_graph,
                                        grad_sink, wanted_uids)

    # Collect reachable nodes (via the recorded topology snapshot, so a
    # weak-held input collected by the GC does not sever its upstream).
    seen = {}
    stack = [root._node]
    while stack:
        node = stack.pop()
        if node.idx in seen:
            continue
        seen[node.idx] = node
        for r in node.in_refs:
            if r.node is not None and r.node.idx not in seen:
                stack.append(r.node)
    order = sorted(seen.values(), key=lambda n: n.idx, reverse=True)

    cot = {(root._uid, root._version): grad}

    for node in order:
        if node.pullback is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True on the first backward)."
            )
        cots = []
        any_live = False
        for uid, ver, (shape, dtype) in zip(
            node.out_uids, node.out_versions, node.out_avals
        ):
            key = (uid, ver)
            if key in cot:
                c = cot.pop(key)
                # a requested INTERMEDIATE's cotangent is complete
                # exactly when its producing node pops it (consumers
                # all ran first in the reverse-topo walk)
                if grad_sink is not None and wanted_uids \
                        and uid in wanted_uids:
                    grad_sink[uid] = (grad_sink[uid] + c
                                      if uid in grad_sink else c)
                cots.append(c)
                any_live = True
            else:
                cots.append(_zero_cotangent(shape, dtype))
        if not any_live:
            continue
        with _obsprofile.backward_scope(node.scope):
            in_grads = node.pullback(
                tuple(cots) if len(cots) > 1 else cots[0])
        for r, g in zip(node.in_refs, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == float0):
                continue
            if r.stop_gradient:
                continue
            if r.node is None:
                if grad_sink is not None:
                    grad_sink[r.uid] = (grad_sink[r.uid] + g
                                        if r.uid in grad_sink else g)
                else:
                    t = r.tensor()
                    if t is not None:
                        t._accumulate_grad(g)
            else:
                key = (r.uid, r.version)
                if key in cot:
                    cot[key] = cot[key] + g
                else:
                    cot[key] = g
        if not retain_graph:
            node.pullback = None
            node.fwd = None
            node.fwd_rng = None


def _backward_differentiable(root, grad, retain_graph, grad_sink=None,
                             wanted_uids=None):
    """create_graph walk: same traversal as backward(), but cotangents
    are Tensors and every pullback runs through the dispatcher, so the
    computed gradients are themselves tape-recorded (differentiable).
    The source graph is implicitly retained (pullbacks are not freed) —
    paddle's create_graph=True implies retain_graph=True likewise."""
    from paddle_tpu.core.dispatch import apply
    from paddle_tpu.core.tensor import Tensor

    if not isinstance(grad, Tensor):
        grad = Tensor(grad, stop_gradient=True)

    seen = {}
    stack = [root._node]
    while stack:
        node = stack.pop()
        if node.idx in seen:
            continue
        seen[node.idx] = node
        for r in node.in_refs:
            if r.node is not None and r.node.idx not in seen:
                stack.append(r.node)
    order = sorted(seen.values(), key=lambda n: n.idx, reverse=True)

    cot = {(root._uid, root._version): grad}

    for node in order:
        if node.pullback is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True on the first backward).")
        cots = []
        tensor_pos = []
        any_live = False
        for uid, ver, (shape, dtype) in zip(
                node.out_uids, node.out_versions, node.out_avals):
            key = (uid, ver)
            if key in cot:
                c = cot.pop(key)
                if grad_sink is not None and wanted_uids \
                        and uid in wanted_uids:
                    grad_sink[uid] = (grad_sink[uid] + c
                                      if uid in grad_sink else c)
                cots.append(c)
                tensor_pos.append(len(cots) - 1)
                any_live = True
            else:
                z = _zero_cotangent(shape, dtype)
                if hasattr(z, "dtype") and z.dtype == float0:
                    cots.append(z)          # stays a closure constant
                else:
                    cots.append(Tensor(z, stop_gradient=True))
                    tensor_pos.append(len(cots) - 1)
        if not any_live:
            continue

        # Re-derive the node's vjp from its stored forward closure with
        # the PRIMAL inputs as live dispatcher arguments — second-order
        # grads must see the primal dependence, which the pullback's
        # baked residuals hide. Falls back to a value-correct but
        # non-differentiable pullback call when the closure is absent
        # (saved_tensors_hooks path) or a primal was mutated/collected.
        primals = [r.tensor() for r in node.in_refs]
        fwd_ok = (node.fwd is not None
                  and all(t is not None and t._version == r.version
                          for t, r in zip(primals, node.in_refs)))
        mask = []
        n_ct = len(tensor_pos)

        if fwd_ok:
            def run_vjp(*ts, _node=node, _cots=cots, _pos=tensor_pos,
                        _mask=mask, _nct=n_ct):
                cs, pvs = ts[:_nct], ts[_nct:]
                full = list(_cots)
                for i, c in zip(_pos, cs):
                    full[i] = c
                # a freshly derived jax.vjp pullback wants the EXACT
                # output structure: a 1-element tuple forward (e.g.
                # split(x, 1)) needs a 1-tuple cotangent, not a bare leaf
                # (the stored pullback normalizes this; this path must
                # use the recorded structure instead of len())
                c = (tuple(full) if (_node.out_is_tuple or len(full) > 1)
                     else full[0])
                # replay the forward's RNG stream: stochastic ops must
                # re-draw the SAME mask, and the re-run must not advance
                # the ambient stream as a side effect
                from paddle_tpu.framework import state as _fstate
                cur = _fstate.get_rng_state()
                if _node.fwd_rng is not None:
                    _fstate.set_rng_state(_node.fwd_rng)
                try:
                    _, pull = jax.vjp(_node.fwd, list(pvs))
                finally:
                    _fstate.set_rng_state(cur)
                (gs,) = pull(c)
                _mask.clear()
                _mask.extend(
                    not (o is None or (hasattr(o, "dtype")
                                       and o.dtype == float0))
                    for o in gs)
                kept = tuple(o for o, m in zip(gs, _mask) if m)
                return kept if len(kept) != 1 else kept[0]

            with _obsprofile.backward_scope(node.scope):
                res = apply(run_vjp, *[cots[i] for i in tensor_pos],
                            *primals)
        else:
            import warnings
            warnings.warn(
                f"create_graph: op '{node.name}' has no differentiable "
                "forward closure (PyLayer / saved_tensors_hooks, or an "
                "input was mutated since the forward) — its gradient "
                "VALUES are correct but second-order terms through it "
                "are dropped", RuntimeWarning, stacklevel=2)

            def run_pb(*cs, _pb=node.pullback, _cots=cots,
                       _pos=tensor_pos, _mask=mask):
                full = list(_cots)
                for i, c in zip(_pos, cs):
                    full[i] = c
                c = tuple(full) if len(full) > 1 else full[0]
                outs = _pb(c)
                _mask.clear()
                _mask.extend(
                    not (o is None or (hasattr(o, "dtype")
                                       and o.dtype == float0))
                    for o in outs)
                kept = tuple(o for o, m in zip(outs, _mask) if m)
                return kept if len(kept) != 1 else kept[0]

            with _obsprofile.backward_scope(node.scope):
                res = apply(run_pb, *[cots[i] for i in tensor_pos])
        res = res if isinstance(res, tuple) else (res,)
        it = iter(res)
        in_grads = [next(it) if m else None for m in mask]

        for r, g in zip(node.in_refs, in_grads):
            if g is None or r.stop_gradient:
                continue
            if r.node is None:
                if grad_sink is not None:
                    grad_sink[r.uid] = (grad_sink[r.uid] + g
                                        if r.uid in grad_sink else g)
                else:
                    t = r.tensor()
                    if t is not None:
                        if t.grad is None:
                            t.grad = g
                        else:
                            t.grad = t.grad + g
            else:
                key = (r.uid, r.version)
                cot[key] = cot[key] + g if key in cot else g
