"""Eager-mode autograd engine: per-op VJP tape.

Reference parity: paddle's C++ autograd engine
(paddle/fluid/eager/backward.cc, grad_node_info) — re-designed for JAX: each
eager op records a `jax.vjp` pullback in a Node; `backward()` walks nodes in
reverse creation order accumulating cotangents. Under `paddle_tpu.jit.to_static`
the same machinery runs on JAX tracers, so the entire forward+backward+update
step fuses into one XLA program — the TPU-native execution model.
"""
from __future__ import annotations

import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

float0 = jax.dtypes.float0


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_state = _GradState()


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(mode: bool):
    _state.enabled = bool(mode)


class no_grad:
    """Context manager / decorator disabling gradient recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = False
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False

    def __call__(self, func):
        import functools

        @functools.wraps(func)
        def wrapper(*a, **kw):
            with no_grad():
                return func(*a, **kw)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = _state.enabled
        _state.enabled = True
        return self

    def __exit__(self, *exc):
        _state.enabled = self._prev
        return False


_node_counter = [0]


class _InRef:
    """One input edge of a Node: identity + topology snapshot.

    Holds the input Tensor STRONGLY by default (pre-existing tape
    semantics: the graph keeps its leaves alive until backward). Under
    `autograd.saved_tensors_hooks` the reference is WEAK — the packed
    form the hook produced is then the only thing the tape retains, so
    offloading an activation to host genuinely releases its device
    buffer once user code drops it. Identity for cotangent routing is
    (uid, version), not id(): uids are never reused, so a collected
    tensor can't alias a later one.
    """

    __slots__ = ("uid", "version", "stop_gradient", "node", "_strong",
                 "_weak")

    def __init__(self, t, weak=False):
        self.uid = t._uid
        self.version = t._version
        self.stop_gradient = t.stop_gradient
        self.node = t._node
        if weak:
            self._strong = None
            self._weak = weakref.ref(t)
        else:
            self._strong = t
            self._weak = None

    def tensor(self):
        return self._strong if self._strong is not None else self._weak()


class Node:
    """One recorded differentiable op."""

    __slots__ = (
        "idx",
        "in_refs",
        "out_uids",
        "out_versions",
        "out_avals",
        "pullback",
        "name",
    )

    def __init__(self, inputs, out_tensors, pullback, name="",
                 weak_inputs=False):
        _node_counter[0] += 1
        self.idx = _node_counter[0]
        self.in_refs = tuple(_InRef(t, weak_inputs) for t in inputs)
        self.out_uids = tuple(t._uid for t in out_tensors)
        self.out_versions = tuple(t._version for t in out_tensors)
        self.out_avals = tuple(
            (tuple(t._value.shape), t._value.dtype) for t in out_tensors
        )
        self.pullback = pullback
        self.name = name

    @property
    def inputs(self):
        """Live input tensors (compat accessor; None for collected
        weak-held inputs)."""
        return tuple(r.tensor() for r in self.in_refs)


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, dtype=float0)


def backward(root, grad=None, retain_graph=False):
    """Run reverse-mode accumulation from `root` tensor into leaf `.grad`s."""
    from paddle_tpu.core.tensor import Tensor

    if root._node is None:
        if not root.stop_gradient:
            # leaf with requires-grad: grad of itself
            g = grad if grad is not None else jnp.ones_like(root._value)
            root._accumulate_grad(g)
        return

    if grad is None:
        if root._value.size != 1:
            raise RuntimeError(
                "grad can be implicitly created only for scalar outputs; "
                f"got shape {root._value.shape}"
            )
        grad = jnp.ones_like(root._value)
    elif isinstance(grad, Tensor):
        grad = grad._value

    # Collect reachable nodes (via the recorded topology snapshot, so a
    # weak-held input collected by the GC does not sever its upstream).
    seen = {}
    stack = [root._node]
    while stack:
        node = stack.pop()
        if node.idx in seen:
            continue
        seen[node.idx] = node
        for r in node.in_refs:
            if r.node is not None and r.node.idx not in seen:
                stack.append(r.node)
    order = sorted(seen.values(), key=lambda n: n.idx, reverse=True)

    cot = {(root._uid, root._version): grad}

    for node in order:
        if node.pullback is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(set retain_graph=True on the first backward)."
            )
        cots = []
        any_live = False
        for uid, ver, (shape, dtype) in zip(
            node.out_uids, node.out_versions, node.out_avals
        ):
            key = (uid, ver)
            if key in cot:
                cots.append(cot.pop(key))
                any_live = True
            else:
                cots.append(_zero_cotangent(shape, dtype))
        if not any_live:
            continue
        in_grads = node.pullback(tuple(cots) if len(cots) > 1 else cots[0])
        for r, g in zip(node.in_refs, in_grads):
            if g is None or (hasattr(g, "dtype") and g.dtype == float0):
                continue
            if r.stop_gradient:
                continue
            if r.node is None:
                t = r.tensor()
                if t is not None:
                    t._accumulate_grad(g)
            else:
                key = (r.uid, r.version)
                if key in cot:
                    cot[key] = cot[key] + g
                else:
                    cot[key] = g
        if not retain_graph:
            node.pullback = None
