"""Version-drift shims over the installed JAX.

The repo targets the modern ``jax.shard_map`` entry point (kwargs
``mesh``/``in_specs``/``out_specs``/``check_vma``); older installs only
ship ``jax.experimental.shard_map.shard_map`` with the ``check_rep``
spelling.  :func:`ensure_shard_map` installs a translating alias onto
the ``jax`` module so every call site — library, tests, and user code
doing ``from jax import shard_map`` — runs against either version
instead of dying with an AttributeError at import or call time.

Called from ``paddle_tpu/__init__`` (and tests/conftest.py, which must
shim before test modules import), so simply importing paddle_tpu makes
the environment whole.
"""
from __future__ import annotations

import jax

__all__ = ["ensure_axis_size", "ensure_shard_map", "install"]


def ensure_shard_map():
    """Return ``jax.shard_map``, installing a compat alias if the
    installed JAX predates the public entry point."""
    # plain getattr would trip jax's deprecation __getattr__ machinery
    # on some versions; the module dict is the honest check
    existing = jax.__dict__.get("shard_map")
    if existing is not None:
        return existing
    try:
        from jax.experimental.shard_map import shard_map as _exp
    except ImportError:      # neither spelling: leave jax untouched
        return None

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma    # modern name -> old spelling
        return _exp(f, mesh=mesh, in_specs=in_specs,
                    out_specs=out_specs, **kw)

    shard_map.__doc__ = _exp.__doc__
    jax.shard_map = shard_map
    return shard_map


def ensure_axis_size():
    """Return ``jax.lax.axis_size``, installing a compat alias on
    installs that predate it (where ``jax.core.axis_frame(name)``
    returns the bound axis size directly)."""
    existing = jax.lax.__dict__.get("axis_size")
    if existing is not None:
        return existing
    import jax.core as _core

    def axis_size(axis_name):
        size = _core.axis_frame(axis_name)
        # modern axis_frame returns a frame object; the old one the size
        return getattr(size, "size", size)

    jax.lax.axis_size = axis_size
    return axis_size


def install():
    """Install every shim; importing paddle_tpu calls this."""
    ensure_shard_map()
    ensure_axis_size()
