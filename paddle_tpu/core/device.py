"""Device / place management.

Reference parity: python/paddle/device/__init__.py (set_device, get_device,
CPUPlace/CUDAPlace/XPUPlace). TPU-first: the native accelerator place is
``TPUPlace``; ``CUDAPlace`` is accepted as an alias for the accelerator so
reference scripts run unmodified.
"""
from __future__ import annotations

import jax


class Place:
    _kind = "unknown"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    def __repr__(self):
        return f"Place({self._kind}:{self._device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._kind == other._kind
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self._kind, self._device_id))

    @property
    def jax_device(self):
        devs = [d for d in jax.devices() if self._kind in (d.platform, "any")]
        if not devs:
            devs = jax.devices()
        return devs[min(self._device_id, len(devs) - 1)]


class CPUPlace(Place):
    _kind = "cpu"

    @property
    def jax_device(self):
        cpus = jax.devices("cpu") if "cpu" in {d.platform for d in jax.devices()} else None
        if cpus:
            return cpus[min(self._device_id, len(cpus) - 1)]
        # No addressable CPU backend registered: fall back to default device.
        return jax.devices()[0]


class TPUPlace(Place):
    _kind = "tpu"

    @property
    def jax_device(self):
        devs = jax.devices()
        return devs[min(self._device_id, len(devs) - 1)]


# Alias so reference code using CUDAPlace targets the accelerator.
class CUDAPlace(TPUPlace):
    pass


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


_current_place = [None]


def _default_place() -> Place:
    if _current_place[0] is None:
        plat = jax.default_backend()
        _current_place[0] = CPUPlace(0) if plat == "cpu" else TPUPlace(0)
    return _current_place[0]


def set_device(device: str) -> Place:
    """set_device("tpu"), set_device("tpu:0"), set_device("cpu"), "gpu" aliases tpu."""
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name == "cpu":
        place = CPUPlace(idx)
    elif name in ("tpu", "gpu", "cuda", "xpu", "npu", "axon"):
        place = TPUPlace(idx)
    else:
        raise ValueError(f"Unknown device {device!r}")
    _current_place[0] = place
    return place


def get_device() -> str:
    p = _default_place()
    return f"{p._kind}:{p._device_id}"


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def device_count() -> int:
    return jax.device_count()
