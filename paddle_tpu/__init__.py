"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on JAX/XLA/Pallas.

API surface mirrors `import paddle` (reference: python/paddle/__init__.py);
execution is TPU-first: eager ops run as JAX primitives with a VJP-tape
autograd, and `paddle_tpu.jit.to_static` compiles whole train steps (forward +
backward + optimizer) into a single XLA program over a `jax.sharding.Mesh`.
"""
from __future__ import annotations

__version__ = "0.1.0"

# version-drift shims FIRST: library modules and user code reference
# `jax.shard_map` / `jax.lax.axis_size`, which older JAX installs only
# ship under other spellings — importing paddle_tpu makes the
# environment whole
from paddle_tpu.core import jax_compat as _jax_compat

_jax_compat.install()

from paddle_tpu.core.tensor import Parameter, Tensor  # noqa: F401
from paddle_tpu.core import dtype as _dtype_mod
from paddle_tpu.core.dtype import (  # noqa: F401
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_rocm,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)

# tensor ops into the root namespace (paddle.add, paddle.reshape, ...)
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu.tensor import einsum  # noqa: F401

from paddle_tpu.core import ops_binding as _ops_binding

_ops_binding.bind_all()

from paddle_tpu.autograd import enable_grad, grad, no_grad, set_grad_enabled  # noqa: F401,E402
from paddle_tpu.framework.state import get_flags, seed, set_flags  # noqa: F401,E402
from paddle_tpu.framework.io import load, save  # noqa: F401,E402

from paddle_tpu import (  # noqa: F401,E402
    amp,
    audio,
    autograd,
    callbacks,
    cost_model,
    dataset,
    device,
    distributed,
    distribution,
    fft,
    framework,
    geometric,
    hub,
    incubate,
    inference,
    io,
    jit,
    linalg,
    metric,
    nn,
    optimizer,
    onnx,
    profiler,
    quantization,
    reader,
    regularizer,
    signal,
    static,
    sparse,
    sysconfig,
    tensor,
    text,
    utils,
    vision,
)
# the function shadows its module at the package root, as in the
# reference (paddle/__init__.py imports and calls it at import time —
# we only call when scipy is actually bundled)
from paddle_tpu.check_import_scipy import check_import_scipy  # noqa: F401,E402,E501
from paddle_tpu.batch import batch  # noqa: F401,E402
from paddle_tpu.hapi.model import Model  # noqa: F401,E402
from paddle_tpu.jit.api import to_static  # noqa: F401,E402
from paddle_tpu.nn.layer.layers import disable_static, enable_static  # noqa: F401,E402


def is_grad_enabled():
    from paddle_tpu.core import engine
    return engine.is_grad_enabled()


def in_dynamic_mode():
    return framework.in_dynamic_mode()


# `paddle.Tensor`-style namespace helpers
def numel(x, name=None):
    return tensor.numel(x)


def is_tensor(x):
    return isinstance(x, Tensor)


def get_cudnn_version():
    return None


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""
    import numpy as _np
    total = [0]
    from paddle_tpu.nn.layer import layers as _L

    def hook(layer, inp, out):
        import paddle_tpu.nn as _nn
        if isinstance(layer, _nn.Linear):
            total[0] += 2 * _np.prod(inp[0].shape) * layer.weight.shape[-1]
        elif isinstance(layer, _nn.Conv2D):
            oshape = out.shape
            k = _np.prod(layer.weight.shape[1:])
            total[0] += 2 * _np.prod(oshape) * k
    hooks = [l.register_forward_post_hook(hook) for l in net.sublayers()]
    import paddle_tpu as _p
    x = _p.zeros(input_size)
    net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]


# ---- remaining reference top-level surface (python/paddle/__init__.py) ----
from paddle_tpu.distributed.parallel import DataParallel  # noqa: E402,F401
from paddle_tpu.nn.initializer import ParamAttr  # noqa: E402,F401


def cast(x, dtype):
    """paddle.cast(x, dtype) (the method form is Tensor.cast)."""
    return x.cast(dtype)


def reverse(x, axis, name=None):
    """Legacy alias of flip (reference keeps both)."""
    return flip(x, axis)


def tolist(x):
    return x.tolist()


def index_add_(x, index, axis, value, name=None):
    """In-place index_add (reference index_add_): same tape semantics as
    the out-of-place op — _inplace_assign adopts the new autograd node so
    gradients flow to `value` (a raw value rebind would drop them)."""
    out = index_add(x, index, axis, value)
    x._inplace_assign(out)
    return x


def frexp(x, name=None):
    """(mantissa, exponent) with x = mantissa * 2**exponent,
    0.5 <= |mantissa| < 1 (reference tensor/math.py frexp)."""
    import jax.numpy as jnp

    from paddle_tpu.core.dispatch import apply
    def fn(v):
        exp = jnp.where(v == 0, 0.0, jnp.floor(jnp.log2(jnp.abs(v))) + 1.0)
        mant = v / jnp.exp2(exp)
        return mant, exp.astype(v.dtype)
    return apply(fn, x)


class iinfo:
    """Integer dtype limits (reference paddle.iinfo)."""

    def __init__(self, dtype):
        import numpy as _np
        info = _np.iinfo(_dtype_mod.convert_dtype(dtype))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


class finfo:
    """Float dtype limits (reference paddle.finfo)."""

    def __init__(self, dtype):
        import jax.numpy as jnp
        import numpy as _np
        name = str(dtype).split(".")[-1]
        info = jnp.finfo(jnp.bfloat16 if name == "bfloat16"
                         else _np.dtype(name))
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(info.tiny)
        self.smallest_normal = float(info.tiny)
        self.bits = int(info.bits)
        self.dtype = name


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Numpy-backed print options (Tensor repr renders through numpy)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def check_shape(shape):
    """Validate a shape argument the way reference creation ops do."""
    if isinstance(shape, Tensor):
        return
    for s in shape:
        if not isinstance(s, (int, Tensor)) or (
                isinstance(s, int) and s < -1):
            raise ValueError(f"invalid shape entry {s!r}")


def disable_signal_handler():
    """The reference unhooks its C++ crash handlers; there are none."""
    return None


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary parity: delegate to hapi Model.summary; a sample
    `input` tensor is forwarded AS-IS so its dtype survives (integer ids
    feed embedding networks correctly)."""
    from paddle_tpu.hapi.model import Model
    return Model(net).summary(input_size=input_size,
                              dtype=dtypes[0] if dtypes else None,
                              input=input)


class LazyGuard:
    """Reference LazyGuard defers parameter materialization; init here is
    host-side numpy (already cheap/lazy-friendly), so the guard is a
    compatibility context manager."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NPUPlace:
    """Reference NPUPlace; no NPU exists on this backend."""

    def __init__(self, device_id=0):
        raise RuntimeError("NPU devices do not exist on the TPU backend; "
                           "use paddle.set_device('tpu')")


def get_cuda_rng_state():
    """No CUDA RNG: the global PRNG key covers every device; returned
    value round-trips through set_cuda_rng_state."""
    from paddle_tpu.framework import state as _state
    return [_state.get_rng_state()] if hasattr(_state, "get_rng_state") \
        else []


def set_cuda_rng_state(state_list):
    from paddle_tpu.framework import state as _state
    if state_list and hasattr(_state, "set_rng_state"):
        _state.set_rng_state(state_list[0])


# paddle.dtype is the dtype TYPE (paddle.dtype('float32') etc.); dtypes
# here are numpy dtypes, so the type is np.dtype
import numpy as _np_mod  # noqa: E402

dtype = _np_mod.dtype


def __getattr__(name):
    # paddle_tpu.onnx loads lazily: its protoc-generated binding needs
    # google.protobuf, which only ONNX exporters should have to carry.
    # paddle_tpu.analysis (tracelint) loads lazily too: it is pure
    # stdlib and the CLI imports it without this package __init__.
    # paddle_tpu.serving lazily as well: the engine compiles nothing at
    # import time, but serving is an opt-in subsystem like onnx export.
    if name in ("onnx", "analysis", "serving", "observability",
                "resilience"):
        import importlib
        return importlib.import_module(f"paddle_tpu.{name}")
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
