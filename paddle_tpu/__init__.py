"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built from scratch on JAX/XLA/Pallas.

API surface mirrors `import paddle` (reference: python/paddle/__init__.py);
execution is TPU-first: eager ops run as JAX primitives with a VJP-tape
autograd, and `paddle_tpu.jit.to_static` compiles whole train steps (forward +
backward + optimizer) into a single XLA program over a `jax.sharding.Mesh`.
"""
from __future__ import annotations

__version__ = "0.1.0"

from paddle_tpu.core.tensor import Parameter, Tensor  # noqa: F401
from paddle_tpu.core import dtype as _dtype_mod
from paddle_tpu.core.dtype import (  # noqa: F401
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    float16,
    float32,
    float64,
    get_default_dtype,
    int8,
    int16,
    int32,
    int64,
    set_default_dtype,
    uint8,
)
from paddle_tpu.core.device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_rocm,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)

# tensor ops into the root namespace (paddle.add, paddle.reshape, ...)
from paddle_tpu.tensor import *  # noqa: F401,F403
from paddle_tpu.tensor import einsum  # noqa: F401

from paddle_tpu.core import ops_binding as _ops_binding

_ops_binding.bind_all()

from paddle_tpu.autograd import enable_grad, grad, no_grad, set_grad_enabled  # noqa: F401,E402
from paddle_tpu.framework.state import get_flags, seed, set_flags  # noqa: F401,E402
from paddle_tpu.framework.io import load, save  # noqa: F401,E402

from paddle_tpu import (  # noqa: F401,E402
    amp,
    audio,
    autograd,
    callbacks,
    device,
    distributed,
    distribution,
    fft,
    framework,
    geometric,
    incubate,
    inference,
    io,
    jit,
    linalg,
    metric,
    nn,
    onnx,
    optimizer,
    profiler,
    quantization,
    signal,
    static,
    sparse,
    tensor,
    text,
    utils,
    vision,
)
from paddle_tpu.batch import batch  # noqa: F401,E402
from paddle_tpu.hapi.model import Model  # noqa: F401,E402
from paddle_tpu.jit.api import to_static  # noqa: F401,E402
from paddle_tpu.nn.layer.layers import disable_static, enable_static  # noqa: F401,E402


def is_grad_enabled():
    from paddle_tpu.core import engine
    return engine.is_grad_enabled()


def in_dynamic_mode():
    return framework.in_dynamic_mode()


# `paddle.Tensor`-style namespace helpers
def numel(x, name=None):
    return tensor.numel(x)


def is_tensor(x):
    return isinstance(x, Tensor)


def get_cudnn_version():
    return None


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py)."""
    import numpy as _np
    total = [0]
    from paddle_tpu.nn.layer import layers as _L

    def hook(layer, inp, out):
        import paddle_tpu.nn as _nn
        if isinstance(layer, _nn.Linear):
            total[0] += 2 * _np.prod(inp[0].shape) * layer.weight.shape[-1]
        elif isinstance(layer, _nn.Conv2D):
            oshape = out.shape
            k = _np.prod(layer.weight.shape[1:])
            total[0] += 2 * _np.prod(oshape) * k
    hooks = [l.register_forward_post_hook(hook) for l in net.sublayers()]
    import paddle_tpu as _p
    x = _p.zeros(input_size)
    net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]


def __getattr__(name):
    # paddle_tpu.onnx loads lazily: its protoc-generated binding needs
    # google.protobuf, which only ONNX exporters should have to carry
    if name == "onnx":
        import importlib
        return importlib.import_module("paddle_tpu.onnx")
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")
