"""bf16 activation-residency + remat policy for to_static training.

BENCH_r05 pinned the train step at ~98.5% HBM bandwidth — bytes, not
flops, are the lever — and the PR 8 roofline attributed the biggest
activation rows to f32-resident tensors that only ever feed bf16
compute.  This module is the storage half of the fix:

- **activation residency** — under an :class:`ActivationPolicy` with a
  ``dtype``, every ``nn.Layer`` boundary casts f32 floating activation
  inputs down to the residency dtype (one ``convert_element_type`` at
  the FIRST boundary; downstream layers see the dtype and keep it).
  Parameters are untouched — they stay f32 master weights, consumed
  through the existing ``amp.auto_cast`` O1 white-list downcasts, and
  the optimizer's f32 update math still reads them at full precision
  (which is also what keeps shardlint SL303 quiet: a param with a
  non-convert consumer is stored f32 on purpose).
- **remat policy** — ``remat=True`` turns on per-block recomputation
  (the model's existing ``distributed.recompute`` units) for the whole
  traced step; ``remat="bf16"`` additionally stores the checkpointed
  region's boundary activations in bf16, so the only live copies of
  the residual stream between forward and backward are half-size.

The policy is trace-scoped, never global: ``to_static(amp_policy=...,
remat=...)`` pushes it for exactly the wrapped function's trace (and
every re-trace), composing with dy2static — eager calls and other
StaticFunctions are unaffected.  ``activation_residency(...)`` is the
same thing as a context manager for eager experiments.

Numerics contract (tested in tests/test_bytesopt.py, documented in
docs/performance_guide.md): params and optimizer math stay f32; the
bf16 activations bound the loss drift — the 20-step gpt-tiny
trajectory stays within the documented tolerance of the f32 run, and
the serving path (which never enables the policy) is token-identical.
Since PR 12 the contract is also enforced STATICALLY: numlint
(analysis/num_rules.py, docs/numlint.md) proves on every audited trace
that masters/moments stay f32 (NL103) and that the optimizer-facing
grad reductions the policy's downcasts induce accumulate wide (NL101 —
F.linear/paddle.matmul own the master downcast inside custom_vjps so
dw/db contract in f32 and land f32).
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

__all__ = ["ActivationPolicy", "activation_residency", "current_policy",
           "remat_active", "residency_dtype"]

_tls = threading.local()


class ActivationPolicy:
    """One trace's mixed-precision storage policy.

    ``dtype``: residency dtype activations are cast to at Layer
    boundaries (None = leave activations alone).  ``remat``: False
    (off), True (recompute blocks, save f32 boundaries), or ``"bf16"``
    (recompute blocks, save bf16 boundaries).
    """

    __slots__ = ("dtype", "remat")

    def __init__(self, dtype="bfloat16", remat=False):
        if dtype is None:
            self.dtype = None
        elif str(dtype) in ("bf16", "bfloat16"):
            self.dtype = jnp.bfloat16
        elif str(dtype) in ("fp16", "float16"):
            self.dtype = jnp.float16
        else:
            # a typo ("bp16") or an unsupported request ("float32")
            # must not silently become fp16 residency
            raise ValueError(
                "activation residency dtype must be None, 'bf16'/"
                f"'bfloat16' or 'fp16'/'float16'; got {dtype!r}")
        if remat not in (False, True, "bf16"):
            raise ValueError(
                f"remat must be False, True or 'bf16'; got {remat!r}")
        self.remat = remat

    # ---- hooks the framework calls ----
    def cast_value(self, v):
        """Residency cast for one raw array: f32 floating -> dtype."""
        if self.dtype is not None and getattr(v, "dtype", None) == \
                jnp.float32:
            return v.astype(self.dtype)
        return v

    def cast_input(self, t):
        """Layer-boundary cast for one positional input (Tensor-aware,
        differentiable — the convert is a recorded op so gradients flow
        back through it)."""
        from paddle_tpu.core.tensor import Tensor
        if self.dtype is None or not isinstance(t, Tensor):
            return t
        if t._value.dtype == jnp.float32:
            return t.astype(self.dtype)
        return t

    def cast_saved(self, v):
        """Storage cast for a recompute region's saved boundary value:
        active only under ``remat="bf16"`` (f32 floating arrays only —
        params lifted into the region are never narrowed)."""
        if self.remat == "bf16" and getattr(v, "dtype", None) == \
                jnp.float32:
            return v.astype(jnp.bfloat16)
        return v

    def __repr__(self):
        return (f"ActivationPolicy(dtype={self.dtype}, "
                f"remat={self.remat!r})")


def current_policy():
    """The ActivationPolicy active on this thread, or None."""
    return getattr(_tls, "policy", None)


def residency_dtype():
    """The active residency dtype, or None when no policy (or a
    remat-only policy) is active."""
    pol = current_policy()
    return pol.dtype if pol is not None else None


def remat_active():
    """The active policy's remat mode (False / True / "bf16")."""
    pol = current_policy()
    return pol.remat if pol is not None else False


@contextlib.contextmanager
def activation_residency(dtype="bfloat16", remat=False):
    """Context manager form of the policy: push an
    :class:`ActivationPolicy` (plus the matching ``amp.auto_cast`` O1
    white-list downcasts when a residency dtype is set) for the dynamic
    extent.  ``to_static(amp_policy=..., remat=...)`` enters this
    around every trace of the wrapped function."""
    from paddle_tpu.amp.auto_cast import auto_cast
    pol = dtype if isinstance(dtype, ActivationPolicy) else \
        ActivationPolicy(dtype, remat=remat)
    prev = getattr(_tls, "policy", None)
    _tls.policy = pol
    try:
        if pol.dtype is not None:
            with auto_cast(enable=True, level="O1",
                           dtype="bfloat16" if pol.dtype == jnp.bfloat16
                           else "float16"):
                yield pol
        else:
            yield pol
    finally:
        _tls.policy = prev
