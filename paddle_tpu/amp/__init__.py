from paddle_tpu.amp.auto_cast import amp_guard, auto_cast, decorate  # noqa: F401
from paddle_tpu.amp.grad_scaler import GradScaler  # noqa: F401
from paddle_tpu.amp import debugging  # noqa: F401
from paddle_tpu.amp.policy import (ActivationPolicy,  # noqa: F401
                                   activation_residency, current_policy,
                                   remat_active, residency_dtype)
