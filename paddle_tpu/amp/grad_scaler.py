"""Dynamic loss scaling. Reference: python/paddle/amp/grad_scaler.py.

Needed for fp16; bf16 on TPU trains unscaled (scaler becomes ~no-op with
enable=False or incr/decr ratios left at defaults but scale 1).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import register_state_tensor


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = Tensor(jnp.asarray(init_loss_scaling if enable else 1.0,
                                         jnp.float32), name="loss_scaling")
        self._scale.persistable = True
        register_state_tensor(self._scale)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        from paddle_tpu.core.dispatch import apply
        return apply(lambda v, s: v * s, var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        with no_grad():
            inv = 1.0 / self._scale._value
            found = jnp.asarray(False)
            for p in optimizer._params():
                if p.grad is not None:
                    g = p.grad._value * inv
                    p.grad._set_value(g)
                    found = found | ~jnp.all(jnp.isfinite(g))
            self._found_inf = bool(found)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale._set_value(jnp.maximum(
                    self._scale._value * self._decr_ratio, 1.0))
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale._set_value(self._scale._value * self._incr_ratio)
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return float(self._scale._value)

    def state_dict(self):
        return {"scale": self._scale.numpy(), "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale._set_value(jnp.asarray(sd["scale"], jnp.float32))
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)
