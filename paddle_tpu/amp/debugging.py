"""paddle_tpu.amp.debugging — NaN/Inf detection.

Reference: python/paddle/amp/debugging.py (TensorCheckerConfig,
enable_operator_stats_collection, check_numerics over the phi
CheckNumericsKernel). TPU-native: jax's debug_nans mode catches the FIRST
NaN-producing primitive op (with a traceback into user code) — strictly
stronger than post-hoc tensor scans — plus an explicit check_numerics for
targeted tensors inside compiled code via checkify-style asserts.
"""
from __future__ import annotations

import contextlib
import enum

import jax
import jax.numpy as jnp


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir


def enable_tensor_checker(config: TensorCheckerConfig):
    """Turn on global NaN detection (jax_debug_nans): every primitive result
    is checked; the first NaN raises with the producing op's traceback."""
    if config.enable:
        jax.config.update("jax_debug_nans", True)


def disable_tensor_checker():
    jax.config.update("jax_debug_nans", False)


@contextlib.contextmanager
def check_nan_inf(enable=True):
    """Scoped NaN/Inf detection."""
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", bool(enable))
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Eagerly verify a tensor is finite; raises FloatingPointError with
    count detail otherwise (reference: paddle.amp.debugging.check_numerics)."""
    from paddle_tpu.core.tensor import Tensor
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if not jnp.issubdtype(v.dtype, jnp.floating) and not jnp.issubdtype(
            v.dtype, jnp.complexfloating):
        return tensor
    nan_ct = int(jnp.isnan(v).sum())
    inf_ct = int(jnp.isinf(v).sum())
    if nan_ct or inf_ct:
        raise FloatingPointError(
            f"check_numerics failed for {op_type or 'tensor'} "
            f"{var_name or ''}: {nan_ct} NaN, {inf_ct} Inf "
            f"(shape {tuple(v.shape)}, dtype {v.dtype})")
    return tensor


def compute_nan_inf_count(tensor):
    from paddle_tpu.core.tensor import Tensor
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    return int(jnp.isnan(v).sum()), int(jnp.isinf(v).sum())
