"""AMP autocast. Reference: python/paddle/amp/auto_cast.py.

TPU-first: the native mixed-precision dtype is bfloat16 (MXU-native, no loss
scaling needed). auto_cast(O1) casts inputs of matmul/conv-class ops to bf16;
O2 ('pure') keeps params in bf16. float16 is accepted and mapped to the same
machinery (with GradScaler doing real loss scaling for fp16).
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from paddle_tpu.core.dtype import convert_dtype

# ops whose inputs are cast down at O1 (matmul/conv-class = MXU ops; each
# implementation calls downcast_inputs(opname=...) at its entry — explicit
# per-op instrumentation, since the generic dispatch funnel has no op names)
WHITE_LIST = {"matmul", "mm", "bmm", "mv", "addmm",
              "conv1d", "conv2d", "conv3d",
              "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
              "linear", "einsum"}
# ops kept in fp32 for stability
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "layer_norm", "norm",
              "mean", "sum", "exp", "log", "logsumexp"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


class auto_cast:
    """Context manager enabling autocast. paddle.amp.auto_cast parity."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16"):
        self.enable = enable
        self.level = level
        self.dtype = jnp.bfloat16 if "bf" in str(dtype) else jnp.float16
        self.white = set(custom_white_list or [])
        self.black = set(custom_black_list or [])

    def __enter__(self):
        self._prev = (_state.enabled, _state.dtype, _state.level,
                      _state.custom_white, _state.custom_black)
        _state.enabled = self.enable
        _state.dtype = self.dtype
        _state.level = self.level
        _state.custom_white = self.white
        _state.custom_black = self.black
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = self._prev
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to bf16/fp16 (master weights stay fp32 inside
    the optimizer's fp32 accumulators). Reference: paddle.amp.decorate."""
    dt = convert_dtype("bfloat16" if "bf" in str(dtype) else "float16")
    single = not isinstance(models, (list, tuple))
    ms = [models] if single else list(models)
    for m in ms:
        m.to(dtype=dt)
    if optimizers is None:
        return models if single else ms
    return (models, optimizers)


def downcast_inputs(*arrays, opname="matmul"):
    """The autocast hook, called INSIDE the op implementations.

    White-listed (MXU-class) ops: fp32 inputs drop to the autocast dtype so
    the contraction runs in bf16. Black-listed ops (incl. custom): low-
    precision inputs are raised to fp32 for stability (matters under O2
    where params live in bf16). Anything else passes through."""
    if not _state.enabled:
        return arrays
    if opname in (_state.custom_black | BLACK_LIST):
        return tuple(
            a.astype(jnp.float32)
            if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16)
            else a for a in arrays)
    if opname in (_state.custom_white | WHITE_LIST):
        return tuple(
            a.astype(_state.dtype)
            if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
            for a in arrays)
    return arrays
