"""paddle.cost_model (reference: python/paddle/cost_model/cost_model.py).

The reference profiles a static Program through the C++ core.CostModel
and ships a GPU op-benchmark JSON. TPU-native: the compiled XLA
executable already carries its own cost model — `profile_measure`
lowers the jitted step, reads XLA's flops / bytes-accessed analysis,
and (optionally) wall-measures a few runs; `get_static_op_time`
serves measured per-op data from a benchmark table captured on this
chip (populated lazily; empty table degrades to analysis-only).
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._static_cost_data = None

    # ---- reference demo-parity helper ----
    def build_program(self):
        """Tiny linear+mean training step (the reference builds the same
        demo program via static.Program). Returns (fn, example_args)
        consumable by profile_measure."""
        import numpy as np

        import paddle_tpu as P
        import paddle_tpu.nn.functional as F

        P.seed(0)
        lin = P.nn.Linear(1, 10)
        opt = P.optimizer.SGD(learning_rate=0.01,
                              parameters=lin.parameters())

        @P.jit.to_static
        def step(x):
            opt.clear_grad()
            loss = lin(x).mean()
            loss.backward()
            opt.step()
            return loss

        x = P.to_tensor(np.random.default_rng(0)
                        .random((10, 1)).astype(np.float32))
        return step, (x,)

    def profile_measure(self, fn, *args, device=None,
                        fetch_cost_list=("time",), iters=3):
        """Compile `fn(*args)` (a StaticFunction or any callable of
        Tensors) and return {"time_ms", "flops", "bytes_accessed",
        "arithmetic_intensity"} from the XLA cost analysis + a short
        wall measurement."""
        out = {}
        fn(*args)  # ensure compiled (and warm)
        entry = None
        compiled = getattr(fn, "_compiled", None)
        if compiled:
            entry = next(iter(compiled.values()))
        if entry is not None:
            jitted, state_list = entry.jitted, entry.state_list
            cost = jitted.lower(
                [t._value for t in state_list],
                [a._value for a in args]).compile().cost_analysis()
            cost = cost[0] if isinstance(cost, (list, tuple)) else cost
            out["flops"] = float(cost.get("flops", 0.0))
            out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
            if out.get("bytes_accessed"):
                out["arithmetic_intensity"] = round(
                    out["flops"] / out["bytes_accessed"], 2)
        if "time" in fetch_cost_list:
            t0 = time.perf_counter()
            for _ in range(iters):
                r = fn(*args)
            blocker = getattr(r, "block_until_ready", None)
            if blocker is not None:
                blocker()
            out["time_ms"] = round(
                (time.perf_counter() - t0) / iters * 1e3, 3)
        return out

    # ---- static benchmark table (reference static_op_benchmark.json) ----
    _TABLE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "static_op_benchmark.json")

    def static_cost_data(self):
        if self._static_cost_data is None:
            try:
                with open(self._TABLE_PATH) as f:
                    self._static_cost_data = json.load(f)
            except OSError:
                self._static_cost_data = []
        return self._static_cost_data

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        if op_name is None:
            raise ValueError(
                "op_name should not be empty when you want to get "
                "static op time")
        if self._static_cost_data is None:
            self.static_cost_data()
        op_cost = {}
        for op_data in self._static_cost_data:
            if op_data.get("op") == op_name and \
                    dtype in op_data.get("config", ""):
                key = "paddle_gpu_time" if forward else \
                    "paddle_gpu_time_backward"
                # measured-on-this-chip tables use "tpu_time*" keys
                tkey = "tpu_time" if forward else "tpu_time_backward"
                op_cost["op_time"] = op_data.get(tkey, op_data.get(key))
                op_cost["config"] = op_data.get("config")
        return op_cost
