"""Global device-mesh state.

TPU-native core of paddle_tpu.distributed: one `jax.sharding.Mesh` over all
devices (ICI-adjacent axes first) plays the role of the reference's process
groups (python/paddle/distributed/collective.py Group). Axes:
  dp — data parallel (gradient psum)
  pp — pipeline stages (ppermute microbatch schedule)
  tp — tensor/model parallel (sharded weights, XLA-inserted collectives)
  sp — sequence/context parallel (long-context; ring attention)
"""
from __future__ import annotations

import threading

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_state = threading.local()
_global_mesh = [None]


def init_mesh(mesh_shape=None, axis_names=None, devices=None):
    """Create + install the global mesh.

    mesh_shape: dict axis->size or tuple sizes; product must equal #devices.
    Default: all devices on the `dp` axis.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_shape is None:
        axis_names = axis_names or ("dp",)
        shape = (n,) * 1 if len(axis_names) == 1 else None
        if shape is None:
            raise ValueError("mesh_shape required for multi-axis mesh")
    elif isinstance(mesh_shape, dict):
        axis_names = tuple(mesh_shape.keys())
        shape = tuple(mesh_shape.values())
    else:
        shape = tuple(mesh_shape)
        axis_names = tuple(axis_names or ("dp", "pp", "tp")[:len(shape)])
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    mesh = Mesh(np.asarray(devices).reshape(shape), axis_names)
    _global_mesh[0] = mesh
    return mesh


def set_mesh(mesh):
    _global_mesh[0] = mesh
    return mesh


def get_mesh():
    return _global_mesh[0]


def ensure_mesh():
    if _global_mesh[0] is None:
        init_mesh()
    return _global_mesh[0]


def axis_size(name):
    m = get_mesh()
    if m is None or name not in m.axis_names:
        return 1
    return m.shape[name]


def resolve_axis_size(axis_name, axis_size=None):
    """Axis size for shard_map bodies: explicit override, else the bound
    axis env (inside shard_map), else the installed mesh — and unlike
    :func:`axis_size`, an axis unknown everywhere is an ERROR, not 1
    (silently degrading to single-device would compute wrong results)."""
    import jax
    if axis_size is not None:
        return int(axis_size)
    try:
        return int(jax.lax.axis_size(axis_name))
    except Exception:
        m = get_mesh()
        if m is None or axis_name not in m.axis_names:
            raise ValueError(f"unknown mesh axis {axis_name!r}")
        return int(m.shape[axis_name])


# ---- collective-axis context (inside shard_map bodies) ----
def push_collective_axis(axis):
    stack = getattr(_state, "coll_axes", None)
    if stack is None:
        stack = _state.coll_axes = []
    stack.append(axis)


def pop_collective_axis():
    _state.coll_axes.pop()


def current_collective_axis():
    stack = getattr(_state, "coll_axes", None)
    return stack[-1] if stack else None


class collective_axis:
    """Context manager marking that code runs inside a shard_map body over
    `axis`, so eager-API collectives (dist.all_reduce etc.) lower to XLA
    psum/all_gather on that axis."""

    def __init__(self, axis):
        self.axis = axis

    def __enter__(self):
        push_collective_axis(self.axis)
        return self

    def __exit__(self, *exc):
        pop_collective_axis()
        return False


def named_sharding(*spec):
    return NamedSharding(ensure_mesh(), P(*spec))


def shard_tensor(t, *spec):
    """Annotate a Tensor with a PartitionSpec; to_static lifts it with this
    sharding (and eagerly places the value if a real multi-device mesh is
    active). Analogue of paddle.distributed.shard_tensor (auto_parallel).

    Axes named in `spec` but absent from the installed mesh degrade to
    replicated, so tp/sp-annotated layers build unchanged on a smaller mesh.
    """
    t.__dict__["dist_spec"] = P(*spec)
    mesh = get_mesh()
    if mesh is not None and len(mesh.devices.flat) > 1 and not isinstance(
            t._value, jax.core.Tracer):
        cleaned = tuple(s if s in mesh.axis_names else None for s in spec)
        t._value = jax.device_put(t._value, NamedSharding(mesh, P(*cleaned)))
    return t


def get_dist_spec(t):
    return t.__dict__.get("dist_spec")
