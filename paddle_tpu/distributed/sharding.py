"""ZeRO-style sharded data parallelism.

Reference: python/paddle/distributed/sharding/group_sharded.py
(GroupShardedOptimizerStage2 / Stage3: shard optimizer state / params across
dp ranks, reduce-scatter grads, all-gather params).

TPU-native: stages are sharding DECLARATIONS, not runtime bookkeeping —
  stage 1/2: optimizer accumulators get a PartitionSpec over `dp`
             (XLA emits ReduceScatter for grads feeding them + AllGather
             when updated params are consumed).
  stage 3:   parameters themselves are sharded over `dp`.
The compiled train step then IS ZeRO: XLA places the reduce-scatter/
all-gather pair on ICI automatically from the shardings.
"""
from __future__ import annotations

import numpy as np

from paddle_tpu.distributed.mesh import shard_tensor


def _shardable(t, axis_size):
    return t._value.ndim >= 1 and t._value.shape[0] % axis_size == 0 and \
        t._value.shape[0] >= axis_size


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False):
    """level: 'os' (stage1), 'os_g' (stage2), 'p_g_os' (stage3)."""
    from paddle_tpu.distributed.mesh import axis_size
    dp = axis_size("dp")
    if dp > 1:
        if level in ("p_g_os",):
            for p in model.parameters():
                if _shardable(p, dp):
                    shard_tensor(p, "dp")
        # optimizer accumulators are created lazily on first step; mark the
        # optimizer so _acc shards them on creation.
        optimizer.__dict__["_shard_accumulators_axis"] = "dp" if level in (
            "os", "os_g", "p_g_os") else None
        _patch_acc(optimizer, dp)
    return model, optimizer, scaler


def _patch_acc(optimizer, dp):
    orig = optimizer._acc

    def acc(name, p, init=0.0, shape=None, dtype=None):
        t = orig(name, p, init, shape, dtype)
        if optimizer.__dict__.get("_shard_accumulators_axis") and \
                _shardable(t, dp) and "dist_spec" not in t.__dict__:
            shard_tensor(t, "dp")
        return t
    optimizer._acc = acc


def save_group_sharded_model(model, output, optimizer=None):
    import paddle_tpu as P
    P.save(model.state_dict(), output + ".pdparams")
    if optimizer is not None:
        P.save(optimizer.state_dict(), output + ".pdopt")
