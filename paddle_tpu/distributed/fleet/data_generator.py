"""Fleet slot data generators (reference:
python/paddle/distributed/fleet/data_generator/data_generator.py —
DataGenerator :20, MultiSlotStringDataGenerator :240,
MultiSlotDataGenerator :285).

Users subclass and implement generate_sample(line); run_from_stdin /
run_from_memory render the MultiSlotDataFeed text format
(`slot_size v1 v2 ... slot_size ...` per sample) that
fleet.InMemoryDataset/QueueDataset files carry.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: return a generator of
        [(slot_name, [value, ...]), ...] per produced sample."""
        raise NotImplementedError(
            "implement generate_sample(line) in your subclass")

    def generate_batch(self, samples):
        """Optional batch-level post-processing hook."""
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def run_from_stdin(self):
        for line in sys.stdin:
            for user_parsed_line in self._iter_samples(line):
                sys.stdout.write(self._gen_str(user_parsed_line))

    def run_from_memory(self):
        """Return the rendered lines instead of streaming stdout."""
        out = []
        for user_parsed_line in self._iter_samples(None):
            out.append(self._gen_str(user_parsed_line))
        return out

    def _iter_samples(self, line):
        gen = self.generate_sample(line)
        if gen is None:
            return
        batch = []
        for sample in gen():
            if sample is None:
                continue
            batch.append(sample)
            if len(batch) == self.batch_size_:
                yield from self.generate_batch(batch)()
                batch = []
        if batch:
            yield from self.generate_batch(batch)()


class MultiSlotStringDataGenerator(DataGenerator):
    """Values are already strings: render `len v1 v2 ...` per slot
    (reference :240)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process()/generate_sample must be a "
                "list or tuple of (name, [str, ...]) pairs")
        parts = []
        for _, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Values are ints/floats; slot dtypes are checked for consistency
    across samples like the reference's proto_info tracking
    (reference :285)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process()/generate_sample must be a "
                "list or tuple of (name, [num, ...]) pairs")
        if self._proto_info is None:
            self._proto_info = []
            for name, values in line:
                kind = "float" if any(isinstance(v, float) for v in values) \
                    else "uint64"
                self._proto_info.append((name, kind))
        elif len(line) != len(self._proto_info):
            raise ValueError(
                f"the complete field set of one sample changed: "
                f"{len(line)} slots vs {len(self._proto_info)}")
        parts = []
        for i, (name, values) in enumerate(line):
            expect_name, kind = self._proto_info[i]
            if name != expect_name:
                raise ValueError(
                    f"slot {i} name changed: {name!r} vs {expect_name!r}")
            if kind == "uint64" and any(
                    isinstance(v, float) for v in values):
                # widen like the reference: once floats appear the slot
                # becomes a float slot
                self._proto_info[i] = (name, "float")
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
