"""paddle.distributed.fleet.meta_parallel.sharding parity surface
(reference: fleet/meta_parallel/sharding/group_sharded_*.py).

The reference implements ZeRO stages with hand-managed GradStorage
buffers, broadcast hooks and a stage-aware scaler on NCCL.  Here the
whole mechanism is `distributed/sharding.py`'s declarative form: stage
levels are sharding annotations over the dp axis and XLA's partitioner
emits the reduce-scatter/all-gather (see group_sharded_parallel).  The
class names below front that implementation so reference-written
training scripts construct the same objects.
"""
from __future__ import annotations

from paddle_tpu.distributed.sharding import (  # noqa: F401
    group_sharded_parallel,
    save_group_sharded_model,
)

__all__ = ["GroupShardedOptimizerStage2", "GroupShardedStage2",
           "GroupShardedStage3", "GroupShardedScaler",
           "group_sharded_parallel", "save_group_sharded_model"]


def GroupShardedOptimizerStage2(params, optim, group=None, offload=False,
                                device="tpu", **kw):
    """Stage-2 optimizer wrapper: optimizer states shard over the dp
    mesh axis as they are (lazily) created — same mechanism
    group_sharded_parallel installs, usable standalone."""
    from paddle_tpu.distributed.mesh import axis_size
    from paddle_tpu.distributed.sharding import _patch_acc

    dp = axis_size("dp")
    if dp > 1:
        optim.__dict__["_shard_accumulators_axis"] = "dp"
        _patch_acc(optim, dp)
    return optim


def GroupShardedStage2(model, optimizer=None, group=None, sync_buffers=False,
                       buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                       device="tpu"):
    if optimizer is not None:
        model, _, _ = group_sharded_parallel(model, optimizer, level="os_g")
        return model
    return model


def GroupShardedStage3(model, optimizer=None, group=None, sync_buffers=False,
                       device="tpu", segment_size=2 ** 20,
                       pertrain_sync_models=True, offload=False, **kw):
    if optimizer is not None:
        model, _, _ = group_sharded_parallel(model, optimizer, level="p_g_os")
        return model
    return model


class GroupShardedScaler:
    """Stage-aware GradScaler facade: bf16 training needs no loss
    scaling on TPU, so this defers to the plain amp.GradScaler."""

    def __new__(cls, scaler):
        return scaler


# flat fused storages shared with fleet.utils (reference keeps twin
# copies in meta_parallel/sharding/group_sharded_storage.py)
from paddle_tpu.distributed.fleet.utils.internal_storage import (  # noqa: E402,F401,E501
    GradStorage,
    InternalStorage,
    ParamStorage,
)

ShardingScaler = GroupShardedScaler   # pre-2.3 alias


class GroupShardedClipGrad:
    """Global-norm clip aware of dp-sharded grads (reference
    group_sharded_utils.py GroupShardedClipGrad): when optimizer states
    shard over dp, each rank holds the full grads here (XLA shards the
    update itself), so the clip reduces to the stock global-norm clip."""

    def __init__(self, clip, device=None, group=None):
        self._clip = clip

    def __call__(self, params_grads):
        return self._clip(params_grads)

    def __getattr__(self, item):
        return getattr(self._clip, item)


ShardingClipGrad = GroupShardedClipGrad   # pre-2.3 alias


def ForwardPreHooks(layer, order_tracer, trainable_params, *a, **kw):
    """Stage-3 gather hook point (reference group_sharded_stage3.py):
    XLA's partitioner all-gathers p_g_os-sharded params at use sites, so
    the hook records traversal order only."""
    order_tracer.setdefault("order", []).append(getattr(layer, "name",
                                                        repr(layer)))


def ForwardPostHooks(layer, *a, **kw):
    """Stage-3 release hook point: rematerialization/partitioning frees
    gathered params after use under XLA; nothing to release by hand."""
    return None
