"""paddle.distributed.fleet.utils parity (reference:
python/paddle/distributed/fleet/utils/)."""
from paddle_tpu.distributed.fleet.utils.fs import (  # noqa: F401
    FS,
    ExecuteError,
    FSFileExistsError,
    FSFileNotExistsError,
    FSShellCmdAborted,
    FSTimeOut,
    HDFSClient,
    LocalFS,
)
from paddle_tpu.distributed.recompute import recompute  # noqa: F401
from paddle_tpu.distributed.fleet.utils.hybrid_parallel_inference import (  # noqa: F401,E501
    DistributedInfer,
    HybridParallelInferenceHelper,
)
from paddle_tpu.distributed.fleet.utils.internal_storage import (  # noqa: F401
    GradStorage,
    InternalStorage,
    ParamStorage,
)


def get_log_level_code():
    import logging
    return logging.getLogger("FLEET").getEffectiveLevel()


def get_log_level_name():
    import logging
    return logging.getLevelName(get_log_level_code())


def set_log_level(level):
    import logging
    logging.getLogger("FLEET").setLevel(level)


def layer_to_str(base, *args, **kwargs):
    """Reference: fleet/utils/log_util.py:63 — repr helper used by the
    hybrid-parallel layer descriptors."""
    parts = [str(a) for a in args]
    parts += [f"{k}={v}" for k, v in kwargs.items()]
    return f"{base}({', '.join(parts)})"
