"""Mesh-aware distributed inference.

Reference:
python/paddle/distributed/fleet/utils/hybrid_parallel_inference.py:23
(HybridParallelInferenceHelper — splits a static inference program over
mp/pp ranks and inserts the send/recv + broadcast plumbing) and
python/paddle/distributed/fleet/utils/ps_util.py:23 (DistributedInfer —
rewrites a program so sparse lookups pull from the parameter server).

TPU-native redesign: there is no program surgery. The model's parameters
are device_put with PartitionSpecs over a ``jax.sharding.Mesh`` (tp/pp
weight shardings), the functionalized forward is jit-compiled once over
the whole mesh, and XLA GSPMD inserts every collective the reference's
helper hand-wires (the mp allreduces, the pp stage hops, the final
broadcast). Serving a request is one pjit call; outputs come back
replicated.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["HybridParallelInferenceHelper", "DistributedInfer"]


class HybridParallelInferenceHelper:
    """Serve a Layer over a device mesh with sharded weights.

    Usage::

        mesh = paddle_tpu.distributed.init_mesh({"mp": 4, "pp": 2})
        helper = HybridParallelInferenceHelper(
            model, mesh, param_specs={"linear.weight": P(None, "mp"), ...})
        out = helper.run(x)            # one pjit call over the mesh

    ``param_specs`` maps state_dict keys (or callable(name, shape) ->
    PartitionSpec) to shardings; unlisted params replicate. The
    reference's micro_batch_size/beam_size generation plumbing is the
    caller's loop here — each ``run`` is one forward.
    """

    def __init__(self, model=None, mesh=None, param_specs=None,
                 num_mp=1, num_pp=1, micro_batch_size=1, beam_size=1,
                 init_comm=True, role_maker=None,
                 startup_program=None, main_program=None):
        from paddle_tpu.distributed.mesh import ensure_mesh
        if model is None:
            raise ValueError(
                "HybridParallelInferenceHelper needs the Layer to serve "
                "(the reference's Program-splitting form has no analogue: "
                "GSPMD partitions the compiled program instead)")
        self.model = model
        self.mesh = mesh or ensure_mesh()
        self.param_specs = param_specs or {}
        model.eval()
        self._shard_params()
        # jax.jit specializes per input shape/dtype internally — one
        # wrapper is the whole cache
        self._fn = jax.jit(self._functional())

    def _spec_for(self, name, value):
        spec = None
        if callable(self.param_specs):
            spec = self.param_specs(name, value.shape)
        else:
            spec = self.param_specs.get(name)
        if spec is None:
            spec = P()                       # replicate
        return spec

    def _shard_params(self):
        """device_put every param with its PartitionSpec over the mesh —
        the analogue of the reference's per-rank program split: each
        device materializes only its weight shards."""
        for name, t in self.model.state_dict().items():
            spec = self._spec_for(name, t)
            t._set_value(jax.device_put(
                t._value, NamedSharding(self.mesh, spec)))

    def _functional(self):
        from paddle_tpu.jit.serialization import functional_forward
        return functional_forward(self.model)

    def run(self, *inputs):
        """One replicated-in, replicated-out forward over the mesh."""
        arrs = [jnp.asarray(np.asarray(x)) for x in inputs]
        # params re-read per call: a set_state_dict between runs must
        # serve the NEW weights (only the compiled fn is cached)
        params = {k: v._value for k, v in self.model.state_dict().items()}
        outs = self._fn(params, *arrs)
        return [np.asarray(o) for o in outs]

    # reference-API no-ops: GSPMD already did the program split
    def gen_infer_program(self, sync_in_while_lastpp2firstpp_var_names=None,
                          sync_in_while_var_names=None,
                          debug=False):
        return None


class DistributedInfer:
    """Inference with beyond-HBM sparse tables left in the parameter
    server (reference ps_util.py:23 DistributedInfer — rewrites the
    program's lookup ops to pull from the PS).

    TPU-native: models built on ``distributed/ps.py`` SparseTable already
    pull rows through jit-safe host callbacks; nothing needs rewriting.
    This helper exposes the reference's API shape: it barriers the
    trainers, optionally warms the local cache, and hands back a callable
    that runs the dense forward on device while embedding lookups stream
    from the host tables.
    """

    def __init__(self, main_program=None, startup_program=None, model=None):
        self.model = model
        self.main_program = main_program
        self.startup_program = startup_program

    def get_dist_infer_program(self):
        # the reference clones + rewrites the program; our lookups are
        # already PS-backed callbacks, so the "dist infer program" IS the
        # model forward
        return self.main_program

    def init_distributed_infer_env(self, exe=None, loss=None,
                                   role_maker=None, dirname=None):
        import paddle_tpu.distributed as dist
        if dist.get_world_size() > 1:
            dist.barrier()
        if dirname and self.model is not None:
            import os

            from paddle_tpu.framework.io import load
            path = dirname
            if os.path.isdir(dirname):
                cands = sorted(
                    f for f in os.listdir(dirname)
                    if f.endswith((".pdparams", ".pkl")))
                if not cands:
                    raise FileNotFoundError(
                        f"no .pdparams/.pkl checkpoint in {dirname}")
                path = os.path.join(dirname, cands[0])
            self.model.set_state_dict(load(path))
        return None

    def run(self, *inputs):
        if self.model is None:
            raise ValueError("DistributedInfer.run needs `model`")
        self.model.eval()
        from paddle_tpu.core.engine import no_grad
        import paddle_tpu as p
        with no_grad():
            arrs = [x if isinstance(x, p.Tensor) else p.to_tensor(x)
                    for x in inputs]
            out = self.model(*arrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(o._value) for o in outs]
