"""Filesystem abstraction for checkpoint/data staging (reference:
python/paddle/distributed/fleet/utils/fs.py — FS :49, LocalFS :111,
HDFSClient further down).

LocalFS is a full implementation on the host filesystem.  HDFSClient
preserves the API but requires a working `hadoop` binary; construction
succeeds (so configs can be built), every operation raises with a clear
message when the binary is absent — this build has no HDFS cluster.
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "ExecuteError",
           "FSFileExistsError", "FSFileNotExistsError", "FSTimeOut",
           "FSShellCmdAborted"]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem tool (reference fs.py:111)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path) or os.path.islink(fs_path):
            os.remove(fs_path)
        else:
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            pass

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    def cat(self, fs_path=None):
        with open(fs_path, "rb") as f:
            return f.read().decode()

    def upload(self, local_path, fs_path):  # local->local copy
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """HDFS via the `hadoop fs` shell (reference fs.py HDFSClient).
    Requires a hadoop binary; absent one, every call raises
    ExecuteError with that explanation instead of hanging."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop")
        self._configs = configs or {}
        self._timeout = time_out / 1000.0

    def _run(self, *args):
        if not os.path.exists(self._hadoop):
            raise ExecuteError(
                f"hadoop binary not found at {self._hadoop}; this "
                f"environment has no HDFS — use LocalFS, or point "
                f"hadoop_home at a real installation")
        cfg = []
        for k, v in self._configs.items():
            cfg += ["-D", f"{k}={v}"]
        cmd = [self._hadoop, "fs"] + cfg + list(args)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=self._timeout)
        except subprocess.TimeoutExpired as e:
            raise FSTimeOut(str(e)) from e
        if out.returncode != 0:
            raise ExecuteError(out.stderr.strip())
        return out.stdout

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)

    def need_upload_download(self):
        return True
