"""Flat fused storages (reference: fleet/utils/internal_storage.py:33
InternalStorage / :94 ParamStorage / :214 GradStorage, and their
meta_parallel/sharding/group_sharded_storage.py twins).

The reference packs many small parameters/gradients into one contiguous
torch buffer and re-points each tensor at a *view*, so NCCL moves one
large message instead of many small ones.  XLA arrays are immutable —
aliasing views is impossible — so here the storage keeps an explicit
offset map and provides pack/unpack both ways: `sync_buffer()` gathers
the current param/grad values into the flat buffer, `sync_views()`
scatters the flat buffer back onto the tensors.  One fused
`all_reduce(storage.buffer)` then has exactly the reference's wire
behavior (single large message over the dp axis).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["InternalStorage", "ParamStorage", "GradStorage"]


def _numel(shape):
    return int(np.prod(shape)) if len(shape) else 1


class InternalStorage:
    """One flat device buffer of `size` elements of `dtype`."""

    def __init__(self, size, dtype, device=None, convert_cpu=False):
        self._size = int(size)
        self._dtype = dtype
        self._device = device or "tpu"
        self.buffer = jnp.zeros((self._size,), dtype=dtype)
        self._fill = 0
        # tensor -> (offset, numel, shape); insertion-ordered
        self._slots = []

    @property
    def size(self):
        return self._size

    def to(self, device, dtype=None, keep_alignment=True):
        if dtype is not None and dtype != self._dtype:
            self.buffer = self.buffer.astype(dtype)
            self._dtype = dtype
        self._device = device
        return self

    # -- packing ----------------------------------------------------------
    def _reserve(self, tensor, align=0):
        n = _numel(tensor.shape)
        if self._fill + n + align > self._size:
            raise ValueError(
                f"storage full: need {n + align} at {self._fill} of "
                f"{self._size}")
        off = self._fill
        self._fill += n + align
        self._slots.append((tensor, off, n, tuple(tensor.shape)))
        return off

    def _write(self, off, n, value):
        self.buffer = self.buffer.at[off:off + n].set(
            jnp.ravel(value).astype(self._dtype))

    def _pack(self, value_of):
        """Rebuild the whole buffer in ONE concatenate (O(N)) — a
        per-slot .at[].set would copy the full immutable buffer once per
        param (O(P*N) on the per-step gradient path).  Alignment gaps and
        the unreserved tail are zero-filled."""
        parts, pos = [], 0
        for t, off, n, _ in self._slots:
            if off > pos:
                parts.append(jnp.zeros((off - pos,), self._dtype))
            v = value_of(t)
            parts.append(jnp.zeros((n,), self._dtype) if v is None
                         else jnp.ravel(v).astype(self._dtype))
            pos = off + n
        if pos < self._size:
            parts.append(jnp.zeros((self._size - pos,), self._dtype))
        if parts:
            self.buffer = jnp.concatenate(parts)

    def sync_views(self):
        """Scatter the flat buffer back onto every registered tensor."""
        for t, off, n, shape in self._slots:
            t._set_value(self.buffer[off:off + n].reshape(shape)
                         .astype(t._value.dtype))


class ParamStorage(InternalStorage):
    """Packs trainable parameters into the flat buffer (reference
    internal_storage.py:94; add_rank_params keeps paddle's signature)."""

    def __init__(self, size, dtype, device=None):
        super().__init__(size, dtype, device)
        self.param2align = {}

    def add_rank_params(self, trainable_params, param2align=None,
                        convert_gpu=False):
        param2align = param2align or {}
        for p in trainable_params:
            align = int(param2align.get(getattr(p, "name", ""), 0))
            self._reserve(p, align)
            self.param2align[getattr(p, "name", str(id(p)))] = align
        self.sync_buffer()

    def sync_buffer(self):
        """Gather current parameter values into the flat buffer (the
        reference's views make this implicit; explicit under XLA)."""
        self._pack(lambda p: p._value)


class GradStorage(InternalStorage):
    """Accumulates many parameters' grads into one flat buffer so the
    dp-axis sync is a single fused message (reference
    internal_storage.py:214; check-in bookkeeping preserved)."""

    def __init__(self, size, dtype, device=None, destination=None,
                 parm2align=None, convert_cpu=False):
        super().__init__(size, dtype, device)
        self._max_size = self._size
        self._release = False
        self.params_checked_in = 0
        self.destination = destination
        self._parm2align = parm2align or {}

    def reset_checked_in(self):
        self.params_checked_in = 0

    @property
    def all_checked_in(self):
        return len(self._slots) == self.params_checked_in

    def can_add_grad_view(self, param, align=0):
        return (self._fill + _numel(param.shape) + align <= self._size
                and not any(t is param for t, *_ in self._slots))

    def add_grad(self, param, align=0):
        self._reserve(param, align)

    def sync_buffer(self):
        """Gather every registered param's .grad into the flat buffer;
        missing grads contribute zeros."""
        def grad_of(p):
            g = getattr(p, "grad", None)
            if g is None:
                return None
            return g._value if hasattr(g, "_value") else g
        self._pack(grad_of)
        self.params_checked_in = len(self._slots)

    def sync_grads(self):
        """Scatter the (e.g. all-reduced) flat buffer back into .grad."""
        from paddle_tpu.core.tensor import Tensor
        for p, off, n, shape in self._slots:
            val = self.buffer[off:off + n].reshape(shape)
            if p.grad is not None:
                p.grad._set_value(val.astype(p.grad._value.dtype))
            else:
                p.grad = Tensor(val.astype(p._value.dtype),
                                stop_gradient=True,
                                name=getattr(p, "name", "param") + "@GRAD")

    def manumal_relase(self):  # sic — reference spells it this way
        if not self._release:
            self.buffer = jnp.zeros((0,), dtype=self._dtype)
            self._release = True

    def rebuild(self):
        if self._release:
            self.buffer = jnp.zeros((self._size,), dtype=self._dtype)
            self.sync_buffer()
            self._release = False
