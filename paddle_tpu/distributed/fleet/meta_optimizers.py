"""paddle.distributed.fleet.meta_optimizers parity surface.

The reference's meta-optimizers rewrite the static Program (insert
c_allreduce, shard states, recompute segments). Under XLA the same
outcomes are sharding annotations + jit: the classes here are honest
fronts that apply the equivalent configuration so reference-written
fleet strategies construct.
"""
from __future__ import annotations

__all__ = ["GradientMergeOptimizer", "LarsOptimizer",
           "ParameterServerOptimizer", "RawProgramOptimizer",
           "dygraph_optimizer"]


class GradientMergeOptimizer:
    """Reference meta_optimizers/gradient_merge_optimizer.py. Real here:
    wraps the inner optimizer in the trace-free k-step accumulator
    (optimizer/gradient_merge.py where-commit form)."""

    def __new__(cls, optimizer=None, k_steps=1, avg=True):
        from paddle_tpu.optimizer.gradient_merge import (
            GradientMergeOptimizer as _GM)
        return _GM(optimizer, k_steps=k_steps, avg=avg)


class LarsOptimizer:
    """Reference meta_optimizers/lars_optimizer.py: swap the inner
    Momentum for LarsMomentum with the strategy's lars configs."""

    def __new__(cls, optimizer=None, lars_coeff=0.001,
                lars_weight_decay=0.0005, epsilon=0.0,
                exclude_from_weight_decay=None):
        from paddle_tpu.optimizer.sgd import LarsMomentum, Momentum
        if not isinstance(optimizer, Momentum):
            # reference lars_optimizer.py _can_apply: LARS only applies
            # to Momentum — other inner optimizers pass through
            # UNCHANGED (scripts with strategy.lars + AdamW train
            # without LARS on reference paddle; don't crash them here)
            import warnings
            warnings.warn(
                "strategy.lars ignored: LarsOptimizer applies to "
                "Momentum (got "
                f"{type(optimizer).__name__})", UserWarning, stacklevel=2)
            return optimizer
        return LarsMomentum(
            learning_rate=optimizer._lr_scheduler
            if optimizer._lr_scheduler is not None
            else float(optimizer._lr_tensor._value),
            momentum=optimizer._momentum,
            lars_coeff=lars_coeff, lars_weight_decay=lars_weight_decay,
            epsilon=epsilon,
            exclude_from_weight_decay=exclude_from_weight_decay,
            parameters=optimizer._parameter_list,
            grad_clip=optimizer._grad_clip)


class RawProgramOptimizer:
    """Reference meta_optimizers/raw_program_optimizer.py: run the user
    program with dp all-reduce only — here that's DataParallel's role."""

    def __init__(self, optimizer=None):
        self.inner_opt = optimizer

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class ParameterServerOptimizer:
    """Reference meta_optimizers/parameter_server_optimizer.py: route
    sparse tables to the PS (distributed/ps.py owns them here)."""

    def __init__(self, optimizer=None):
        self.inner_opt = optimizer

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class dygraph_optimizer:
    """Submodule-style namespace (reference
    meta_optimizers/dygraph_optimizer/): sharded dygraph optimizers."""

    @staticmethod
    def DygraphShardingOptimizer(hcg=None, user_defined_strategy=None,
                                 params=None, inner_optimizer_class=None,
                                 **inner_kw):
        """Stage-1 sharding: optimizer states shard over dp (reference
        dygraph_sharding_optimizer.py) — the existing stage-2 wrapper
        subsumes it (states are the stage-1 subset of stage-2)."""
        from paddle_tpu.distributed.fleet.meta_parallel_sharding import (
            GroupShardedOptimizerStage2)
        from paddle_tpu.optimizer.optimizer import Optimizer
        if inner_optimizer_class is None:
            raise ValueError(
                "DygraphShardingOptimizer needs inner_optimizer_class "
                "(e.g. paddle_tpu.optimizer.AdamW) — there is no inner "
                "optimizer to shard otherwise")
        opt = (inner_optimizer_class
               if isinstance(inner_optimizer_class, Optimizer)
               else inner_optimizer_class(parameters=params, **inner_kw))
        return GroupShardedOptimizerStage2(params, opt)

    @staticmethod
    def ShardingOptimizerStage2(params=None, optim=None, group=None,
                                offload=False, **kw):
        from paddle_tpu.distributed.fleet.meta_parallel_sharding import (
            GroupShardedOptimizerStage2)
        return GroupShardedOptimizerStage2(params, optim, group=group,
                                           offload=offload, **kw)


