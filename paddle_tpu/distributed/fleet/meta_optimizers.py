"""paddle.distributed.fleet.meta_optimizers parity surface.

The reference's meta-optimizers rewrite the static Program (insert
c_allreduce, shard states, recompute segments). Under XLA the same
outcomes are sharding annotations + jit: the classes here are honest
fronts that apply the equivalent configuration so reference-written
fleet strategies construct.
"""
from __future__ import annotations

__all__ = ["ParameterServerOptimizer", "RawProgramOptimizer",
           "dygraph_optimizer"]


class RawProgramOptimizer:
    """Reference meta_optimizers/raw_program_optimizer.py: run the user
    program with dp all-reduce only — here that's DataParallel's role."""

    def __init__(self, optimizer=None):
        self.inner_opt = optimizer

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class ParameterServerOptimizer:
    """Reference meta_optimizers/parameter_server_optimizer.py: route
    sparse tables to the PS (distributed/ps.py owns them here)."""

    def __init__(self, optimizer=None):
        self.inner_opt = optimizer

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)


class dygraph_optimizer:
    """Submodule-style namespace (reference
    meta_optimizers/dygraph_optimizer/): sharded dygraph optimizers."""

    @staticmethod
    def DygraphShardingOptimizer(hcg=None, user_defined_strategy=None,
                                 params=None, inner_optimizer_class=None,
                                 **inner_kw):
        """Stage-1 sharding: optimizer states shard over dp (reference
        dygraph_sharding_optimizer.py) — the existing stage-2 wrapper
        subsumes it (states are the stage-1 subset of stage-2)."""
        from paddle_tpu.distributed.fleet.meta_parallel_sharding import (
            GroupShardedOptimizerStage2)
        from paddle_tpu.optimizer.optimizer import Optimizer
        if inner_optimizer_class is None:
            raise ValueError(
                "DygraphShardingOptimizer needs inner_optimizer_class "
                "(e.g. paddle_tpu.optimizer.AdamW) — there is no inner "
                "optimizer to shard otherwise")
        opt = (inner_optimizer_class
               if isinstance(inner_optimizer_class, Optimizer)
               else inner_optimizer_class(parameters=params, **inner_kw))
        return GroupShardedOptimizerStage2(params, opt)

    @staticmethod
    def ShardingOptimizerStage2(params=None, optim=None, group=None,
                                offload=False, **kw):
        from paddle_tpu.distributed.fleet.meta_parallel_sharding import (
            GroupShardedOptimizerStage2)
        return GroupShardedOptimizerStage2(params, optim, group=group,
                                           offload=offload, **kw)


