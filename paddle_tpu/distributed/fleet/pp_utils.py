"""paddle.distributed.fleet.meta_parallel.pp_utils parity.

Reference: fleet/meta_parallel/pp_utils/p2p_communication.py
(recv_forward/send_backward/… — the NCCL point-to-point calls the
reference's pipeline schedule is built from) and pp_utils/utils.py.

TPU-native: there is no one-sided send. In the SPMD rendering every
matched send/recv PAIR is ONE `lax.ppermute` over the `pp` mesh axis —
stage s's send_forward and stage s+1's recv_forward are the same
collective. These helpers expose the reference's vocabulary for code
being ported: each returns the tensor that ARRIVES at this stage (the
value the reference's recv would produce), and the "send" names are
aliases of the paired receive since the pair is one op. Call them
inside `shard_map` over a mesh with a `pp` axis (the prebuilt schedules
in distributed/pipeline.py are the fast path; these are the primitives).
"""
from __future__ import annotations

import numpy as np

from jax import lax

from paddle_tpu.distributed import mesh as mesh_mod

__all__ = [
    "p2p_shift", "recv_forward", "recv_backward", "send_forward",
    "send_backward", "send_forward_recv_backward",
    "send_backward_recv_forward", "get_tensor_bytes", "is_float_tensor",
]


def p2p_shift(x, direction=+1, axis_name="pp", axis_size=None):
    """One ring hop over `axis_name`: +1 moves values stage s -> s+1
    (the forward-activation direction), -1 moves s -> s-1 (the
    backward-cotangent direction)."""
    n = mesh_mod.resolve_axis_size(axis_name, axis_size)
    perm = [(i, (i + direction) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def recv_forward(tensor, axis_name="pp", axis_size=None):
    """The activation arriving FROM the previous stage (the reference's
    recv_forward); `tensor` is this stage's outgoing activation — the
    send half of the same ppermute."""
    return p2p_shift(tensor, +1, axis_name, axis_size)


def recv_backward(tensor, axis_name="pp", axis_size=None):
    """The cotangent arriving FROM the next stage."""
    return p2p_shift(tensor, -1, axis_name, axis_size)


# one collective per matched pair: the send names ARE the paired recv
send_forward = recv_forward
send_backward = recv_backward


def send_forward_recv_backward(activation, cotangent, axis_name="pp",
                               axis_size=None):
    """1F1B steady-state exchange: push the activation one stage ahead
    and pull the cotangent one stage back (two ppermutes, opposite
    directions — XLA overlaps them)."""
    return (p2p_shift(activation, +1, axis_name, axis_size),
            p2p_shift(cotangent, -1, axis_name, axis_size))


def send_backward_recv_forward(cotangent, activation, axis_name="pp",
                               axis_size=None):
    return (p2p_shift(cotangent, -1, axis_name, axis_size),
            p2p_shift(activation, +1, axis_name, axis_size))


def get_tensor_bytes(tensor):
    """Byte size of a tensor (reference pp_utils/utils.py)."""
    v = getattr(tensor, "_value", tensor)
    return int(np.prod(v.shape)) * v.dtype.itemsize


def is_float_tensor(tensor):
    import jax.numpy as jnp
    v = getattr(tensor, "_value", tensor)
    return jnp.issubdtype(v.dtype, jnp.floating)
