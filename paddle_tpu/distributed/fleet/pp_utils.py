"""paddle.distributed.fleet.meta_parallel.pp_utils parity.

Reference: fleet/meta_parallel/pp_utils/p2p_communication.py
(recv_forward/send_backward/… — the NCCL point-to-point calls the
reference's pipeline schedule is built from) and pp_utils/utils.py.

TPU-native: there is no one-sided send. In the SPMD rendering every
matched send/recv PAIR is ONE `lax.ppermute` over the `pp` mesh axis —
stage s's send_forward and stage s+1's recv_forward are the same
collective. These helpers expose the reference's vocabulary for code
being ported: each returns the tensor that ARRIVES at this stage (the
value the reference's recv would produce), and the "send" names are
aliases of the paired receive since the pair is one op. Call them
inside `shard_map` over a mesh with a `pp` axis (the prebuilt schedules
in distributed/pipeline.py are the fast path; these are the primitives).
"""
from __future__ import annotations

import numpy as np

from jax import lax

from paddle_tpu.distributed import mesh as mesh_mod

__all__ = [
    "p2p_shift", "recv_forward", "recv_backward", "send_forward",
    "send_backward", "send_forward_recv_backward",
    "send_backward_recv_forward", "get_tensor_bytes", "is_float_tensor",
    "SendRecvMeta", "initialize_p2p_groups", "allgather_partial",
    "send_partial", "recv_partial", "send_forward_recv_forward",
    "send_backward_recv_backward",
    "send_forward_backward_recv_forward_backward",
]


def p2p_shift(x, direction=+1, axis_name="pp", axis_size=None):
    """One ring hop over `axis_name`: +1 moves values stage s -> s+1
    (the forward-activation direction), -1 moves s -> s-1 (the
    backward-cotangent direction)."""
    n = mesh_mod.resolve_axis_size(axis_name, axis_size)
    perm = [(i, (i + direction) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def recv_forward(tensor, axis_name="pp", axis_size=None):
    """The activation arriving FROM the previous stage (the reference's
    recv_forward); `tensor` is this stage's outgoing activation — the
    send half of the same ppermute."""
    return p2p_shift(tensor, +1, axis_name, axis_size)


def recv_backward(tensor, axis_name="pp", axis_size=None):
    """The cotangent arriving FROM the next stage."""
    return p2p_shift(tensor, -1, axis_name, axis_size)


# one collective per matched pair: the send names ARE the paired recv
send_forward = recv_forward
send_backward = recv_backward


def send_forward_recv_backward(activation, cotangent, axis_name="pp",
                               axis_size=None):
    """1F1B steady-state exchange: push the activation one stage ahead
    and pull the cotangent one stage back (two ppermutes, opposite
    directions — XLA overlaps them)."""
    return (p2p_shift(activation, +1, axis_name, axis_size),
            p2p_shift(cotangent, -1, axis_name, axis_size))


def send_backward_recv_forward(cotangent, activation, axis_name="pp",
                               axis_size=None):
    return (p2p_shift(cotangent, -1, axis_name, axis_size),
            p2p_shift(activation, +1, axis_name, axis_size))


def send_forward_recv_forward(tensor, axis_name="pp", axis_size=None):
    """Interleave steady state: relay — the activation moves one stage
    ahead while this stage receives the previous stage's (one ppermute:
    both halves of the reference pair are the same collective)."""
    return p2p_shift(tensor, +1, axis_name, axis_size)


def send_backward_recv_backward(tensor, axis_name="pp", axis_size=None):
    return p2p_shift(tensor, -1, axis_name, axis_size)


def send_forward_backward_recv_forward_backward(
        activation, cotangent, axis_name="pp", axis_size=None):
    """Both relays of the interleaved steady state in one call
    (reference p2p_communication.py's fused four-way op)."""
    return (p2p_shift(activation, +1, axis_name, axis_size),
            p2p_shift(cotangent, -1, axis_name, axis_size))


class SendRecvMeta:
    """Shape/dtype metadata the reference exchanges before dynamic-shape
    p2p (p2p_communication.py SendRecvMeta). XLA p2p is static-shape, so
    the meta is captured at trace time and never hits the wire."""

    def __init__(self):
        self.send_shape_message = None
        self.send_dtype_message = None
        self.recv_shape_message = None
        self.recv_dtype_message = None

    def set_send_message(self, tensor):
        v = getattr(tensor, "_value", tensor)
        self.send_shape_message = tuple(v.shape)
        self.send_dtype_message = str(v.dtype)

    def recv_meta(self, tensor):
        v = getattr(tensor, "_value", tensor)
        self.recv_shape_message = tuple(v.shape)
        self.recv_dtype_message = str(v.dtype)


def initialize_p2p_groups(hcg=None, *a, **kw):
    """NCCL p2p group setup in the reference; the mesh owns comms here —
    validate a pp axis exists and return it."""
    m = mesh_mod.get_mesh()
    if m is not None and "pp" not in m.axis_names:
        raise ValueError(f"mesh {m.axis_names} has no 'pp' axis")
    return m


def _mp_slice(tensor, axis_name="mp"):
    """This rank's 1/mp slice of a flattened tensor (pad-free only when
    divisible — reference send_partial has the same restriction)."""
    import jax
    import jax.numpy as jnp
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    flat = jnp.ravel(tensor)
    if flat.shape[0] % n:
        raise ValueError(f"numel {flat.shape[0]} not divisible by {n}")
    k = flat.shape[0] // n
    return jax.lax.dynamic_slice(flat, (idx * k,), (k,))


def send_partial(tensor, direction=+1, axis_name="pp", mp_axis="mp",
                 axis_size=None):
    """Reference send_partial: ship only this mp-rank's 1/mp slice over
    the pp hop (cuts wire bytes mp-fold); pair with allgather_partial."""
    return p2p_shift(_mp_slice(tensor, mp_axis), direction, axis_name,
                     axis_size)


recv_partial = send_partial  # one collective per matched pair


def allgather_partial(part, mp_axis="mp", shape=None):
    """Reassemble a send_partial slice: all_gather over the mp axis,
    then restore the original shape."""
    import jax.numpy as jnp
    full = lax.all_gather(part, mp_axis, tiled=True)
    return full if shape is None else jnp.reshape(full, shape)


def get_tensor_bytes(tensor):
    """Byte size of a tensor (reference pp_utils/utils.py)."""
    v = getattr(tensor, "_value", tensor)
    return int(np.prod(v.shape)) * v.dtype.itemsize


def is_float_tensor(tensor):
    import jax.numpy as jnp
    v = getattr(tensor, "_value", tensor)
    return jnp.issubdtype(v.dtype, jnp.floating)
