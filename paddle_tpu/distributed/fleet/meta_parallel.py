"""Tensor/pipeline-parallel layers.

Reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
(mp_layers.py ColumnParallelLinear/RowParallelLinear/VocabParallelEmbedding,
pp_layers.py PipelineLayer).

TPU-native design: instead of manually splitting weights per rank + inserting
c_allreduce ops, each layer holds the FULL logical weight annotated with a
PartitionSpec on the `tp` mesh axis. Under pjit, XLA partitions the matmul
and inserts the reduce (RowParallel) / gather (gather_output) collectives on
ICI automatically — same math, compiler-placed communication.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.distributed.mesh import get_dist_spec, shard_tensor
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer


def _constrain(x, *spec):
    """with_sharding_constraint when a multi-device mesh is active.

    Spec entries naming axes absent from the installed mesh degrade to
    replicated (None), so tp/sp-annotated layers run unchanged on e.g. a
    pure-dp mesh.
    """
    from paddle_tpu.distributed.mesh import get_mesh
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = get_mesh()
    if mesh is None or len(mesh.devices.flat) == 1:
        return x
    cleaned = tuple(s if s in mesh.axis_names else None for s in spec)
    return apply(lambda v: jax.lax.with_sharding_constraint(
        v, NamedSharding(mesh, PartitionSpec(*cleaned))), x)


class ColumnParallelLinear(Layer):
    """W: [in, out] sharded over tp on the OUT (column) dim."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        shard_tensor(self.weight, None, "tp")
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)
            shard_tensor(self.bias, "tp")

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = _constrain(y)  # replicated -> XLA all-gathers
        else:
            y = _constrain(y, *([None] * (len(y.shape) - 1)), "tp")
        return y


class RowParallelLinear(Layer):
    """W: [in, out] sharded over tp on the IN (row) dim; XLA inserts the
    partial-sum AllReduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        shard_tensor(self.weight, "tp", None)
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)

    def forward(self, x):
        if self.input_is_parallel:
            x = _constrain(x, *([None] * (len(x.shape) - 1)), "tp")
        y = F.linear(x, self.weight, self.bias)
        return _constrain(y)  # replicated output => psum over tp


class VocabParallelEmbedding(Layer):
    """Embedding table sharded over tp on the vocab dim."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        shard_tensor(self.weight, "tp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return _constrain(out)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel CE for the pjit/propagation path.

    Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:500
    (ParallelCrossEntropy → c_softmax_with_cross_entropy). TPU-native: the
    logits' class dim stays sharded over `tp` through the whole loss — the
    log-sum-exp reduces the sharded dim directly (XLA inserts the max/sum
    collectives) and the target logit is extracted with a one-hot
    multiply-sum that propagation shards the same way. No replicated
    [..., V] tensor is ever materialized, matching the explicit-collectives
    primitive in fleet/mp_ops.py (vocab_parallel_cross_entropy)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        ignore_index = self.ignore_index

        def fn(logits, lab):
            v = logits.shape[-1]
            # keep the class dim sharded over tp (no-op off-mesh)
            lf = logits.astype(jnp.float32)
            m = jax.lax.stop_gradient(
                jnp.max(lf, axis=-1, keepdims=True))
            shifted = lf - m
            lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1)) + m[..., 0]
            lab_i = lab.astype(jnp.int32)
            safe = jnp.clip(lab_i, 0, v - 1)
            onehot = jax.nn.one_hot(safe, v, dtype=lf.dtype)
            tgt = jnp.sum(shifted * onehot, axis=-1) + m[..., 0]
            nll = lse - tgt
            return jnp.where(lab_i == ignore_index, 0.0, nll)

        squeeze = len(label.shape) == len(input.shape)
        lab = label.reshape(label.shape[:-1]) if squeeze else label
        x = _constrain(input, *([None] * (len(input.shape) - 1)), "tp")
        out = apply(fn, x, lab)
        return out.unsqueeze(-1) if squeeze else out


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr=
                 "weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """Reference: pp_layers.py PipelineLayer.

    Holds the full LayerList; `num_stages` records the intended pipeline
    split. In the TPU design the stage boundary materializes when the train
    step is compiled: paddle_tpu.distributed.pipeline.pipeline_forward runs
    stages under shard_map over the `pp` axis with ppermute microbatch
    rotation (see distributed/pipeline.py). Single-mesh execution (pp=1)
    runs the layers sequentially.
    """

    def __init__(self, layers, num_stages=1, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, **kwargs):
        super().__init__()
        from paddle_tpu.nn.layer.container import LayerList
        built = [d.build_layer() if isinstance(d, LayerDesc) else d
                 for d in layers]
        self.run_function = LayerList(built)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval

    def forward(self, x):
        from paddle_tpu.distributed.recompute import recompute as _rc
        for i, layer in enumerate(self.run_function):
            if self.recompute_interval > 0 and i % self.recompute_interval == 0 \
                    and self.training:
                x = _rc(layer, x)
            else:
                x = layer(x)
        return x

    def get_stage_layers(self, stage_id):
        n = len(self.run_function)
        per = (n + self.num_stages - 1) // self.num_stages
        return list(self.run_function)[stage_id * per:(stage_id + 1) * per]
