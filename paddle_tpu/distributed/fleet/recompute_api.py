"""Segmented/hybrid recompute entry points (reference:
fleet/recompute/recompute.py:512 recompute_sequential,
recompute_hybrid.py:234 recompute_hybrid) — implemented next to the
core recompute so parameter lifting is shared."""
from paddle_tpu.distributed.recompute import (  # noqa: F401
    recompute,
    recompute_hybrid,
    recompute_sequential,
)

__all__ = ["recompute_sequential", "recompute_hybrid"]
