"""paddle.distributed.fleet.layers.mpu parity (reference:
fleet/layers/mpu/ — the model-parallel layer/op vocabulary).

The layers live in fleet.meta_parallel (full logical weights with tp
PartitionSpecs; XLA places the collectives) and are re-exported here at
the reference's path. `split` is the reference's one-call model-parallel
constructor (mp_ops.py:678): build the matching tp-sharded layer for an
embedding/linear operation.
"""
from __future__ import annotations

from paddle_tpu.distributed.fleet.meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from paddle_tpu.distributed.fleet.mp_ops import (  # noqa: F401
    copy_to_tp_region,
    reduce_from_tp_region,
    vocab_parallel_cross_entropy,
    vocab_parallel_embedding,
)

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "split"]


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Build + apply the tp-sharded layer for `operation` in one call
    (reference mp_ops.py:678). operation='embedding' -> vocab-parallel
    embedding; 'linear' with axis=0 -> row-parallel, axis=1 ->
    column-parallel. The mesh's tp axis plays num_partitions' role — XLA
    shards the weight; num_partitions is validated against it."""
    from paddle_tpu.distributed.mesh import axis_size

    tp = axis_size("tp")
    if num_partitions not in (1, tp):
        raise ValueError(
            f"num_partitions={num_partitions} but the mesh tp axis has "
            f"{tp} devices — size the mesh, not the call")
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"unsupported operation {operation!r}")
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  has_bias=bias_attr is not False,
                                  input_is_parallel=False)
    elif axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out)
    else:
        raise ValueError("axis must be 0 (row) or 1 (column)")
    return layer(x)
