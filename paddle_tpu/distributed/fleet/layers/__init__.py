"""paddle.distributed.fleet.layers parity namespace."""
from paddle_tpu.distributed.fleet.layers import mpu  # noqa: F401
