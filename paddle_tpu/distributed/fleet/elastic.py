"""paddle.distributed.fleet.elastic parity (reference:
fleet/elastic/__init__.py enable_elastic/launch_elastic +
elastic/manager.py ElasticLevel/ElasticStatus/LauncherInterface +
elastic/collective.py CollectiveLauncher).

The reference coordinates restarts through etcd; here the elastic
machinery is distributed/elastic.py's watchdog/heartbeat manager (no
external KV store — jax.distributed owns membership), and these names
front it at the reference's import path.
"""
from __future__ import annotations

import os
import signal
import time

from paddle_tpu.distributed.elastic import (  # noqa: F401
    ElasticManager,
    HeartbeatServer,
    Watchdog,
)

__all__ = ["ElasticLevel", "ElasticStatus", "LauncherInterface",
           "CollectiveLauncher", "ElasticManager", "enable_elastic",
           "launch_elastic"]


class ElasticLevel:
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class LauncherInterface:
    """Process-group launcher base (reference elastic/manager.py:55)."""

    def __init__(self, args):
        self.args = args
        self.procs = []

    def _terminate_procs(self):
        for p in self.procs:
            proc = getattr(p, "proc", p)
            if proc is not None and proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 10
        while time.time() < deadline:
            if all(getattr(p, "proc", p) is None or
                   getattr(p, "proc", p).poll() is not None
                   for p in self.procs):
                return True
            time.sleep(0.2)
        for p in self.procs:
            proc = getattr(p, "proc", p)
            if proc is not None and proc.poll() is None and os.name != "nt":
                proc.send_signal(signal.SIGKILL)
        return False

    def launch(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError

    def watch(self):
        raise NotImplementedError


class CollectiveLauncher(LauncherInterface):
    """Launch + watch the local trainer group (reference
    elastic/collective.py:28), backed by utils.start_local_trainers."""

    def __init__(self, args):
        super().__init__(args)
        self.tmp_dir = getattr(args, "log_dir", None)

    def launch(self):
        from paddle_tpu.distributed.utils import (
            get_cluster_from_args, get_gpus, start_local_trainers)
        args = self.args
        devices = get_gpus(getattr(args, "gpus", None))
        cluster, pod = get_cluster_from_args(args, devices)
        self.procs = start_local_trainers(
            cluster, pod, args.training_script,
            getattr(args, "training_script_args", []),
            log_dir=self.tmp_dir)
        return self.procs

    def watch(self):
        from paddle_tpu.distributed.utils import watch_local_trainers
        try:
            alive = watch_local_trainers(self.procs, len(self.procs))
        except RuntimeError:
            return ElasticStatus.ERROR
        return ElasticStatus.HOLD if alive else ElasticStatus.COMPLETED

    def stop(self):
        self._terminate_procs()


def enable_elastic(args, distribute_mode=None):
    """Elastic runs are opted into via PADDLE_ELASTIC_TIMEOUT (the
    reference keys off its etcd server setting)."""
    return bool(os.environ.get("PADDLE_ELASTIC_TIMEOUT"))


def launch_elastic(args, distribute_mode=None):
    """Launch under the elastic manager: start trainers, watch, restart
    on failure up to PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL retries."""
    retries = int(os.environ.get("PADDLE_ELASTIC_FAULT_TOLERANC_LEVEL", 1))
    launcher = CollectiveLauncher(args)
    for attempt in range(max(retries, 1)):
        launcher.launch()
        while True:
            status = launcher.watch()
            if status == ElasticStatus.COMPLETED:
                return ElasticStatus.COMPLETED
            if status == ElasticStatus.ERROR:
                launcher.stop()
                break
            time.sleep(1.0)
    return ElasticStatus.ERROR
