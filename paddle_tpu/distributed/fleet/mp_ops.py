"""Functional vocab-parallel primitives for explicit shard_map programs.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:37
(VocabParallelEmbedding: per-rank table slice + masked lookup + allreduce)
and :500 (ParallelCrossEntropy → c_softmax_with_cross_entropy, the fused
sharded-logits CE with two allreduces).

TPU-native: these are pure functions over LOCAL shards, meant to be called
inside a shard_map body whose table/logits are partitioned over the `tp`
mesh axis on the vocab dim. The layer classes in meta_parallel.py cover the
pjit/propagation path; these cover the explicit-collectives path (the
hybrid GPT flagship) where no full-vocab tensor may ever materialize.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def vocab_parallel_embedding(table_local, ids, axis_name="tp"):
    """Gather rows of a vocab-sharded embedding table.

    table_local: [V/tp, H] — this shard's contiguous slice of the table
                 (shard i holds global rows [i*V/tp, (i+1)*V/tp)).
    ids:         integer array of GLOBAL vocab ids, any shape.
    Returns [*ids.shape, H], replicated over `axis_name` (one psum).
    Out-of-shard ids contribute zero locally; the psum assembles the row
    from whichever shard owns it — Megatron's masked-lookup + allreduce.
    """
    idx = lax.axis_index(axis_name)
    v_loc = table_local.shape[0]
    local = ids.astype(jnp.int32) - idx * v_loc
    ok = (local >= 0) & (local < v_loc)
    rows = table_local[jnp.clip(local, 0, v_loc - 1)]
    rows = jnp.where(ok[..., None], rows, 0)
    return lax.psum(rows, axis_name)


def vocab_parallel_cross_entropy(logits_local, labels, axis_name="tp"):
    """Softmax cross-entropy over vocab-sharded logits.

    logits_local: [..., V/tp] — this shard's slice of the class dim.
    labels:       [...] GLOBAL class ids.
    Returns per-token nll [...], replicated over `axis_name`.

    No [..., V] tensor is ever formed: the softmax runs as a local
    max/sum-exp plus pmax+psum over the vocab axis, and the target logit is
    fetched by the owning shard only (masked + psum) — the TPU analogue of
    the reference's fused c_softmax_with_cross_entropy.
    """
    idx = lax.axis_index(axis_name)
    v_loc = logits_local.shape[-1]
    # global max via all_gather (pmax has no AD rule, even under
    # stop_gradient — the tracer reaches it first); the shift is a
    # constant wrt grad, the standard logsumexp trick
    m = lax.stop_gradient(jnp.max(
        lax.all_gather(jnp.max(logits_local, axis=-1), axis_name), axis=0))
    denom = lax.psum(
        jnp.sum(jnp.exp(logits_local - m[..., None]), axis=-1), axis_name)
    local_lab = labels.astype(jnp.int32) - idx * v_loc
    ok = (local_lab >= 0) & (local_lab < v_loc)
    tgt = jnp.take_along_axis(
        logits_local, jnp.clip(local_lab, 0, v_loc - 1)[..., None],
        axis=-1)[..., 0]
    tgt = lax.psum(jnp.where(ok, tgt, 0.0), axis_name)
    return jnp.log(denom) + m - tgt
