"""Functional vocab-parallel primitives for explicit shard_map programs.

Reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py:37
(VocabParallelEmbedding: per-rank table slice + masked lookup + allreduce)
and :500 (ParallelCrossEntropy → c_softmax_with_cross_entropy, the fused
sharded-logits CE with two allreduces).

TPU-native: these are pure functions over LOCAL shards, meant to be called
inside a shard_map body whose table/logits are partitioned over the `tp`
mesh axis on the vocab dim. The layer classes in meta_parallel.py cover the
pjit/propagation path; these cover the explicit-collectives path (the
hybrid GPT flagship) where no full-vocab tensor may ever materialize.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------------------
# Tensor-parallel region boundary ops (explicit-backward path)
#
# Reference: fleet/layers/mpu/mp_ops.py `_c_identity` (identity fwd,
# allreduce bwd) and `_c_allreduce`/`_mp_allreduce` (allreduce fwd, identity
# bwd) — the Megatron region-boundary pair. They matter here because
# jax.vjp taken INSIDE a shard_map with check_vma=False transposes
# lax.psum to another psum, over-counting replicated cotangents by the
# axis size; whole-program outer AD self-corrects, an inner vjp (the 1F1B
# pipeline's per-stage backward) does not. These two custom-VJP ops pin the
# correct semantics for inner vjps: use them (not bare lax.psum) in any
# code differentiated by an explicit per-stage vjp.
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name):
    """Identity forward; backward all-reduces the cotangent over
    `axis_name`. Insert where a replicated activation enters per-shard
    compute (e.g. before a column-parallel matmul)."""
    return x


def _copy_fwd(x, axis_name):
    return x, None


def _copy_bwd(axis_name, _, ct):
    return (lax.psum(ct, axis_name),)


copy_to_tp_region.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp_region(x, axis_name):
    """All-reduce forward; backward passes the cotangent through
    untouched. Use in place of lax.psum after a row-parallel matmul when
    the surrounding code is differentiated with an explicit jax.vjp."""
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, ct):
    return (ct,)


reduce_from_tp_region.defvjp(_reduce_fwd, _reduce_bwd)


def vocab_parallel_embedding(table_local, ids, axis_name="tp",
                             explicit_bwd=False):
    """Gather rows of a vocab-sharded embedding table.

    table_local: [V/tp, H] — this shard's contiguous slice of the table
                 (shard i holds global rows [i*V/tp, (i+1)*V/tp)).
    ids:         integer array of GLOBAL vocab ids, any shape.
    Returns [*ids.shape, H], replicated over `axis_name` (one psum).
    Out-of-shard ids contribute zero locally; the psum assembles the row
    from whichever shard owns it — Megatron's masked-lookup + allreduce.

    explicit_bwd=True switches the allreduce to the identity-backward
    region op — required when the caller differentiates with an explicit
    jax.vjp (1F1B pipeline) rather than whole-program AD.
    """
    idx = lax.axis_index(axis_name)
    v_loc = table_local.shape[0]
    local = ids.astype(jnp.int32) - idx * v_loc
    ok = (local >= 0) & (local < v_loc)
    rows = table_local[jnp.clip(local, 0, v_loc - 1)]
    rows = jnp.where(ok[..., None], rows, 0)
    if explicit_bwd:
        return reduce_from_tp_region(rows, axis_name)
    return lax.psum(rows, axis_name)


def vocab_parallel_cross_entropy(logits_local, labels, axis_name="tp",
                                 explicit_bwd=False):
    """Softmax cross-entropy over vocab-sharded logits.

    logits_local: [..., V/tp] — this shard's slice of the class dim.
    labels:       [...] GLOBAL class ids.
    Returns per-token nll [...], replicated over `axis_name`.

    No [..., V] tensor is ever formed: the softmax runs as a local
    max/sum-exp plus pmax+psum over the vocab axis, and the target logit is
    fetched by the owning shard only (masked + psum) — the TPU analogue of
    the reference's fused c_softmax_with_cross_entropy.
    """
    # custom_vjp rejects keyword args at call time — bind positionally
    if explicit_bwd:
        def reduce(x):
            return reduce_from_tp_region(x, axis_name)
    else:
        def reduce(x):
            return lax.psum(x, axis_name)
    idx = lax.axis_index(axis_name)
    v_loc = logits_local.shape[-1]
    # global max via all_gather (pmax has no AD rule, even under
    # stop_gradient — the tracer reaches it first); the shift is a
    # constant wrt grad, the standard logsumexp trick
    m = lax.stop_gradient(jnp.max(
        lax.all_gather(jnp.max(logits_local, axis=-1), axis_name), axis=0))
    denom = reduce(
        jnp.sum(jnp.exp(logits_local - m[..., None]), axis=-1))
    local_lab = labels.astype(jnp.int32) - idx * v_loc
    ok = (local_lab >= 0) & (local_lab < v_loc)
    tgt = jnp.take_along_axis(
        logits_local, jnp.clip(local_lab, 0, v_loc - 1)[..., None],
        axis=-1)[..., 0]
    tgt = reduce(jnp.where(ok, tgt, 0.0))
    return jnp.log(denom) + m - tgt


def parallel_margin_cross_entropy(logits_local, labels, margin1=1.0,
                                  margin2=0.5, margin3=0.0, scale=64.0,
                                  axis_name="tp", return_softmax=False,
                                  explicit_bwd=False):
    """ArcFace margin softmax over CLASS-SHARDED cosine logits.

    Reference: python/paddle/nn/functional/loss.py margin_cross_entropy's
    group-parallel path (c_margin_cross_entropy: each rank owns a class
    shard; only the rank owning the target class applies the margin, then
    the softmax runs as the usual two-allreduce sharded logsumexp).

    logits_local: [N, C/tp] cosine similarities (this shard's classes).
    labels:       [N] GLOBAL class ids.
    Returns per-sample nll [N] (replicated over `axis_name`); with
    return_softmax=True also the LOCAL softmax shard [N, C/tp].
    """
    if explicit_bwd:
        def reduce(x):
            return reduce_from_tp_region(x, axis_name)
    else:
        def reduce(x):
            return lax.psum(x, axis_name)
    idx = lax.axis_index(axis_name)
    v_loc = logits_local.shape[-1]
    local_lab = labels.reshape(-1).astype(jnp.int32) - idx * v_loc
    ok = (local_lab >= 0) & (local_lab < v_loc)
    # stay inside arccos' differentiable domain (cos==±1 -> d/dx = ∓inf)
    cos = jnp.clip(logits_local, -1.0 + 1e-6, 1.0 - 1e-6)
    tgt_cos = jnp.take_along_axis(
        cos, jnp.clip(local_lab, 0, v_loc - 1)[:, None], axis=-1)[..., 0]
    theta = jnp.arccos(tgt_cos)
    adjusted_tgt = jnp.cos(margin1 * theta + margin2) - margin3
    onehot_local = (jnp.arange(v_loc)[None, :] == local_lab[:, None]) & \
        ok[:, None]
    z = jnp.where(onehot_local, adjusted_tgt[:, None], cos) * scale
    # sharded logsumexp CE inlined (same math as
    # vocab_parallel_cross_entropy) so the softmax branch reuses m/denom
    # instead of issuing a second all_gather + psum pair
    m = lax.stop_gradient(jnp.max(
        lax.all_gather(jnp.max(z, axis=-1), axis_name), axis=0))
    denom = reduce(jnp.sum(jnp.exp(z - m[..., None]), axis=-1))
    tgt = jnp.take_along_axis(
        z, jnp.clip(local_lab, 0, v_loc - 1)[:, None], axis=-1)[..., 0]
    tgt = reduce(jnp.where(ok, tgt, 0.0))
    nll = jnp.log(denom) + m - tgt
    if not return_softmax:
        return nll
    softmax_local = jnp.exp(z - m[..., None]) / denom[..., None]
    return nll, softmax_local
