"""Fleet distributed-training API. Reference: python/paddle/distributed/fleet/.

TPU-native mapping:
  fleet.init(strategy) — builds the hybrid Mesh (dp × pp × tp × sp) from
      strategy.hybrid_configs (the analogue of HybridCommunicateGroup's
      process-group topology).
  fleet.distributed_model(model) — annotates parameter shardings (replicated
      on dp; meta_parallel layers carry their own tp specs).
  fleet.distributed_optimizer(opt) — returns the optimizer unchanged: grad
      sync is an XLA AllReduce inserted by sharding propagation when the step
      is jit'd over the mesh (no NCCL hooks to install).
"""
from __future__ import annotations

import jax

from paddle_tpu.distributed.fleet.meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    PipelineLayer,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from paddle_tpu.distributed.fleet import utils  # noqa: F401
from paddle_tpu.distributed.fleet import elastic  # noqa: F401
from paddle_tpu.distributed.fleet import layers  # noqa: F401
from paddle_tpu.distributed.fleet import meta_optimizers  # noqa: F401
from paddle_tpu.distributed.fleet import mp_ops  # noqa: F401
from paddle_tpu.distributed.fleet import pp_utils  # noqa: F401
from paddle_tpu.distributed.fleet.dataset import (  # noqa: F401
    InMemoryDataset,
    QueueDataset,
)
from paddle_tpu.distributed.fleet.data_generator import (  # noqa: F401
    DataGenerator,
    MultiSlotDataGenerator,
    MultiSlotStringDataGenerator,
)


class DistributedStrategy:
    """Reference: fleet/base/distributed_strategy.py (protobuf-backed).
    Plain attribute bag with the commonly used knobs.

    WIRED flags (they change behavior here): ``hybrid_configs`` (mesh
    shape), ``lars`` (+``lars_configs``), ``gradient_merge``
    (+``gradient_merge_configs``) — both applied by
    ``fleet.distributed_optimizer``.  Every OTHER truthy flag is
    accepted for reference-code compatibility but currently a no-op in
    the TPU-native mapping (amp belongs to ``paddle_tpu.amp``,
    recompute to the model config / ``to_static(remat=)``, sharding/
    pipeline to the mesh axes); ``fleet.init`` emits one
    ``UserWarning`` per ignored flag so a silently-dropped knob can
    never masquerade as applied.
    """

    # truthy values of these attributes are accepted but NOT wired to
    # anything — fleet.init warns per flag (see class docstring)
    _UNWIRED_FLAGS = ("amp", "recompute", "sharding", "pipeline",
                      "dgc", "lamb", "localsgd", "adaptive_localsgd",
                      "find_unused_parameters")

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.lars = False
        self.lars_configs = {}
        self.dgc = False
        self.localsgd = False
        self.localsgd_configs = {}
        self.adaptive_localsgd = False
        self.find_unused_parameters = False
        self.without_graph_optimization = True


def _warn_ignored_flags(strategy):
    """One explicit ``UserWarning`` per truthy-but-unwired
    DistributedStrategy flag (VERDICT Weak #3: these used to no-op
    silently).  Returns the ignored flag names (tested)."""
    import warnings
    ignored = []
    for flag in DistributedStrategy._UNWIRED_FLAGS:
        if getattr(strategy, flag, False):
            ignored.append(flag)
            warnings.warn(
                f"DistributedStrategy.{flag} is not wired in the "
                f"TPU-native fleet mapping and is IGNORED (see "
                f"DistributedStrategy docstring for the supported "
                f"set)", UserWarning, stacklevel=3)
    hc = getattr(strategy, "hybrid_configs", None) or {}
    if (hc.get("sharding_degree", 1) or 1) > 1:
        ignored.append("hybrid_configs.sharding_degree")
        warnings.warn(
            "hybrid_configs.sharding_degree > 1 is not wired (ZeRO "
            "sharding is future work) and is IGNORED in the mesh "
            "build", UserWarning, stacklevel=3)
    return ignored


class _HybridCommunicateGroup:
    """Topology info (reference: fleet/base/topology.py). Axis sizes come
    from the global mesh."""

    def __init__(self, mesh):
        self._mesh = mesh

    def _axis(self, name):
        return self._mesh.shape[name] if (
            self._mesh is not None and name in self._mesh.axis_names) else 1

    def get_data_parallel_world_size(self):
        return self._axis("dp")

    def get_model_parallel_world_size(self):
        return self._axis("tp")

    def get_pipe_parallel_world_size(self):
        return self._axis("pp")

    def get_sharding_parallel_world_size(self):
        return self._axis("dp")

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="tp")

    def get_data_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="dp")

    def get_pipe_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="pp")


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        from paddle_tpu.distributed import mesh as dmesh
        self._strategy = strategy or DistributedStrategy()
        _warn_ignored_flags(self._strategy)
        hc = self._strategy.hybrid_configs
        n = jax.device_count()
        dp = hc.get("dp_degree", 1) or 1
        mp = hc.get("mp_degree", 1) or 1
        pp = hc.get("pp_degree", 1) or 1
        sep = hc.get("sep_degree", 1) or 1
        prod = dp * mp * pp * sep
        if prod == 1 and n > 1:
            dp = n
            prod = n
        if prod != n:
            raise ValueError(
                f"hybrid degrees dp{dp}*mp{mp}*pp{pp}*sep{sep}={prod} != "
                f"{n} devices")
        shape = {"dp": dp, "pp": pp, "sp": sep, "tp": mp}
        mesh = dmesh.init_mesh(shape)
        self._hcg = _HybridCommunicateGroup(mesh)
        self._initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        from paddle_tpu.distributed.mesh import get_dist_spec, shard_tensor
        for p in model.parameters():
            if get_dist_spec(p) is None:
                shard_tensor(p)  # replicated
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        strategy = strategy or getattr(self, "_strategy", None)
        if strategy is None:
            return optimizer
        if getattr(strategy, "lars", False):
            from paddle_tpu.distributed.fleet.meta_optimizers import (
                LarsOptimizer)
            cfg = dict(strategy.lars_configs or {})
            optimizer = LarsOptimizer(
                optimizer,
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                epsilon=cfg.get("epsilon", 0.0),
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay", None))
        if getattr(strategy, "gradient_merge", False):
            from paddle_tpu.distributed.fleet.meta_optimizers import (
                GradientMergeOptimizer)
            cfg = dict(strategy.gradient_merge_configs or {})
            optimizer = GradientMergeOptimizer(
                optimizer, k_steps=cfg.get("k_steps", 1),
                avg=cfg.get("avg", True))
        return optimizer

    @property
    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()

    def is_first_worker(self):
        return jax.process_index() == 0

    def barrier_worker(self):
        from paddle_tpu.distributed.collective import barrier
        barrier()


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group


def worker_num():
    return jax.process_count()


def worker_index():
    return jax.process_index()


# public aliases matching reference fleet/__init__.py naming
Fleet = _Fleet
HybridCommunicateGroup = _HybridCommunicateGroup


class CommunicateTopology:
    """Axis-name <-> coordinate mapping over the hybrid mesh (reference
    fleet/base/topology.py CommunicateTopology)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"), dims=None):
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
        # 'sharding' stays 1 unless explicitly configured: it reuses the
        # dp ranks (ZeRO over dp), so mapping it to the dp SIZE would
        # double-count dp in world_size/rank arithmetic
        name_map = {"data": "dp", "pipe": "pp", "model": "tp",
                    "sep": "sp"}
        self._names = list(hybrid_group_names)
        if dims is not None:
            self._dims = list(dims)
        elif mesh is not None:
            self._dims = [mesh.shape.get(name_map[n], 1)
                          if n in name_map else 1 for n in self._names]
        else:
            self._dims = [1] * len(self._names)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        out = 1
        for d in self._dims:
            out *= d
        return out

    def get_rank(self, **coords):
        rank = 0
        for n, d in zip(self._names, self._dims):
            rank = rank * d + coords.get(n, 0)
        return rank

    def get_coord(self, rank):
        import collections
        coords = []
        for d in reversed(self._dims):
            coords.append(rank % d)
            rank //= d
        C = collections.namedtuple("Coord", [n.replace("-", "_")
                                             for n in self._names])
        return C(*reversed(coords))


class Role:
    """reference fleet/base/role_maker.py Role constants."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Collective role maker: every process is a worker; identity comes
    from jax.distributed (reference role_maker.py PaddleCloudRoleMaker)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_num(self):
        return jax.process_count()

    def _worker_index(self):
        return jax.process_index()

    def _is_first_worker(self):
        return jax.process_index() == 0

    def _role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)
        self._kwargs = kwargs


class UtilBase:
    """reference fleet/base/util_factory.py UtilBase: small cross-worker
    helpers on top of the collective API."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        import paddle_tpu as P
        from paddle_tpu.distributed.collective import ReduceOp, all_reduce
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        t = P.to_tensor(np.asarray(input))
        return np.asarray(all_reduce(t, op=op)._value)

    def barrier(self, comm_world="worker"):
        from paddle_tpu.distributed.collective import barrier
        barrier()

    def all_gather(self, input, comm_world="worker"):
        import numpy as np

        import paddle_tpu as P
        from paddle_tpu.distributed.collective import all_gather
        out = []
        all_gather(out, P.to_tensor(np.asarray(input)))
        return [np.asarray(t._value) for t in out]

    def get_file_shard(self, files):
        n = jax.process_count()
        i = jax.process_index()
        return files[i::n]

    def print_on_rank(self, message, rank_id=0):
        if jax.process_index() == rank_id:
            print(message)


util = UtilBase()
_Fleet.util = util


def get_logger(name="FLEET", level=None, fmt=None):
    from paddle_tpu.distributed.utils.launch_utils import (
        get_logger as _gl,
    )
    return _gl(log_level=level, name=name)


# single canonical implementation lives in distributed.utils.launch_utils
from paddle_tpu.distributed.utils.launch_utils import (  # noqa: E402,F401
    find_free_ports,
    get_host_name_ip,
)


# reference layout parity: fleet.meta_parallel.sharding is a subpackage;
# here meta_parallel is a module, so the sharding surface mounts as an
# attribute + sys.modules entry (both import spellings work)
import sys as _sys  # noqa: E402

from paddle_tpu.distributed.fleet import meta_parallel as _mp  # noqa: E402
from paddle_tpu.distributed.fleet import (  # noqa: E402
    meta_parallel_sharding as _mps,
)

_mp.sharding = _mps
_sys.modules[__name__ + ".meta_parallel.sharding"] = _mps

from paddle_tpu.distributed.fleet import pp_utils as _ppu  # noqa: E402

_mp.pp_utils = _ppu
_sys.modules[__name__ + ".meta_parallel.pp_utils"] = _ppu


# ---- launch-plumbing surface (reference fleet/launch_utils.py) ----
# the canonical classes live in distributed.utils.launch_utils; the
# reference exposes them from the fleet namespace too
from paddle_tpu.distributed.utils.launch_utils import (  # noqa: E402,F401
    Cluster,
    Hdfs,
    JobServer,
    Pod,
    Trainer,
    TrainerProc,
    get_cluster,
    get_logger as _llu_get_logger,
    terminate_local_procs,
)
from paddle_tpu.distributed.fleet import base  # noqa: E402,F401


class DistributeMode:
    """fleetrun launch mode ids (reference launch_utils.py:38)."""

    COLLECTIVE = 0
    PS = 1
    PS_HETER = 2


class DeviceMode:
    """Training device type ids (reference launch_utils.py:48); TPU is
    the accelerator here — mapped onto the collective/XPU slot."""

    UNKNOWN = -1
    CPU = 0
    GPU = 1
    KUNLUN = 2
    XPU = 2
    ASCEND_NPU = 3
    MLU = 4
    TPU = 5
