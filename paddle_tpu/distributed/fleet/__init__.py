"""Fleet distributed-training API. Reference: python/paddle/distributed/fleet/.

TPU-native mapping:
  fleet.init(strategy) — builds the hybrid Mesh (dp × pp × tp × sp) from
      strategy.hybrid_configs (the analogue of HybridCommunicateGroup's
      process-group topology).
  fleet.distributed_model(model) — annotates parameter shardings (replicated
      on dp; meta_parallel layers carry their own tp specs).
  fleet.distributed_optimizer(opt) — returns the optimizer unchanged: grad
      sync is an XLA AllReduce inserted by sharding propagation when the step
      is jit'd over the mesh (no NCCL hooks to install).
"""
from __future__ import annotations

import jax

from paddle_tpu.distributed.fleet.meta_parallel import (  # noqa: F401
    ColumnParallelLinear,
    PipelineLayer,
    RowParallelLinear,
    VocabParallelEmbedding,
)


class DistributedStrategy:
    """Reference: fleet/base/distributed_strategy.py (protobuf-backed).
    Plain attribute bag with the commonly used knobs."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.dgc = False
        self.find_unused_parameters = False
        self.without_graph_optimization = True


class _HybridCommunicateGroup:
    """Topology info (reference: fleet/base/topology.py). Axis sizes come
    from the global mesh."""

    def __init__(self, mesh):
        self._mesh = mesh

    def _axis(self, name):
        return self._mesh.shape[name] if (
            self._mesh is not None and name in self._mesh.axis_names) else 1

    def get_data_parallel_world_size(self):
        return self._axis("dp")

    def get_model_parallel_world_size(self):
        return self._axis("tp")

    def get_pipe_parallel_world_size(self):
        return self._axis("pp")

    def get_sharding_parallel_world_size(self):
        return self._axis("dp")

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_model_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="tp")

    def get_data_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="dp")

    def get_pipe_parallel_group(self):
        from paddle_tpu.distributed.collective import Group
        return Group(axis="pp")


class _Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None):
        from paddle_tpu.distributed import mesh as dmesh
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        n = jax.device_count()
        dp = hc.get("dp_degree", 1) or 1
        mp = hc.get("mp_degree", 1) or 1
        pp = hc.get("pp_degree", 1) or 1
        sep = hc.get("sep_degree", 1) or 1
        prod = dp * mp * pp * sep
        if prod == 1 and n > 1:
            dp = n
            prod = n
        if prod != n:
            raise ValueError(
                f"hybrid degrees dp{dp}*mp{mp}*pp{pp}*sep{sep}={prod} != "
                f"{n} devices")
        shape = {"dp": dp, "pp": pp, "sp": sep, "tp": mp}
        mesh = dmesh.init_mesh(shape)
        self._hcg = _HybridCommunicateGroup(mesh)
        self._initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        from paddle_tpu.distributed.mesh import get_dist_spec, shard_tensor
        for p in model.parameters():
            if get_dist_spec(p) is None:
                shard_tensor(p)  # replicated
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        return optimizer

    @property
    def worker_num(self):
        return jax.process_count()

    def worker_index(self):
        return jax.process_index()

    def is_first_worker(self):
        return jax.process_index() == 0

    def barrier_worker(self):
        from paddle_tpu.distributed.collective import barrier
        barrier()


fleet = _Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group


def worker_num():
    return jax.process_count()


def worker_index():
    return jax.process_index()
