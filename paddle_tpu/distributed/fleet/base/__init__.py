"""paddle.distributed.fleet.base parity namespace.

Reference: python/paddle/distributed/fleet/base/ (topology.py
CommunicateTopology/HybridCommunicateGroup, role_maker.py,
strategy_group.py DPGroup/MPGroup/PPGroup/ShardingGroup/
OrthogonalStrategy, util_factory.py UtilBase).

TPU-native: the topology/role classes are thin views over the installed
jax.sharding.Mesh (one SPMD program, no per-rank processes to
choreograph); strategy groups wrap distributed.new_group so collective
calls can still be scoped the reference's way.
"""
from __future__ import annotations

from paddle_tpu.distributed.fleet import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    PaddleCloudRoleMaker,
    UserDefinedRoleMaker,
    UtilBase,
)

__all__ = [
    "CommunicateTopology", "HybridCommunicateGroup",
    "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "UtilBase",
    "StrategyGroupBase", "DPGroup", "MPGroup", "PPGroup",
    "ShardingGroup", "OrthogonalStrategy",
]


class StrategyGroupBase:
    """One parallelism axis's process groups (reference
    fleet/base/strategy_group.py StrategyGroupBase): built from rank
    lists; `group` is the group containing this rank (or the list when
    several do)."""

    def __init__(self, list_of_ranks):
        import paddle_tpu.distributed as dist
        self._list_of_ranks = list(list_of_ranks)
        rank = dist.get_rank()
        groups = [dist.new_group(rs) for rs in self._list_of_ranks]
        mine = [g for g, rs in zip(groups, self._list_of_ranks)
                if rank in rs]
        self._group = mine[0] if len(mine) == 1 else (mine or groups)

    @property
    def group(self):
        return self._group

    @property
    def world_size(self):
        sizes = {len(rs) for rs in self._list_of_ranks}
        return sizes.pop() if len(sizes) == 1 else \
            [len(rs) for rs in self._list_of_ranks]


class DPGroup(StrategyGroupBase):
    pass


class MPGroup(StrategyGroupBase):
    pass


class ShardingGroup(StrategyGroupBase):
    pass


class PPGroup(StrategyGroupBase):
    """Pipeline groups additionally expose the p2p neighbor ranks the
    reference's send/recv schedule uses; in the SPMD rendering these are
    the ppermute peers."""

    def __init__(self, list_of_ranks):
        super().__init__(list_of_ranks)
        import paddle_tpu.distributed as dist
        rank = dist.get_rank()
        self._rank_of_next_stage = None
        self._rank_of_prev_stage = None
        for rs in self._list_of_ranks:
            if rank in rs:
                i = rs.index(rank)
                self._rank_of_next_stage = rs[(i + 1) % len(rs)]
                self._rank_of_prev_stage = rs[(i - 1) % len(rs)]

    @property
    def rank_of_next_stage(self):
        return self._rank_of_next_stage

    @property
    def rank_of_prev_stage(self):
        return self._rank_of_prev_stage


class OrthogonalStrategy:
    """Compose orthogonal parallelism axes (reference strategy_group.py
    OrthogonalStrategy): list of (name, degree, group_cls); rank lists
    are the mesh-order cartesian slices, plus fused groups over unions
    of axes."""

    def __init__(self, list_of_strategy, fused_strategy_dict=None):
        import itertools

        import paddle_tpu.distributed as dist
        self._strategies = {}
        names = [s[0] for s in list_of_strategy]
        degrees = [s[1] for s in list_of_strategy]
        world = 1
        for d in degrees:
            world *= d
        if dist.get_world_size() not in (1, world):
            raise ValueError(
                f"strategy degrees {degrees} produce world {world} != "
                f"{dist.get_world_size()}")
        self._degrees = dict(zip(names, degrees))
        # rank layout: row-major over the strategy order
        coords = list(itertools.product(*[range(d) for d in degrees]))
        rank_of = {c: i for i, c in enumerate(coords)}
        for ax, (nm, d, cls) in enumerate(list_of_strategy):
            lists = {}
            for c in coords:
                key = c[:ax] + c[ax + 1:]
                lists.setdefault(key, []).append(rank_of[c])
            self._strategies[nm] = cls(sorted(lists.values()))
        self._fused = {}
        for fname, axes in (fused_strategy_dict or {}).items():
            ax_ids = [names.index(a) for a in axes]
            lists = {}
            for c in coords:
                key = tuple(v for i, v in enumerate(c) if i not in ax_ids)
                lists.setdefault(key, []).append(rank_of[c])
            self._fused[fname] = StrategyGroupBase(sorted(lists.values()))

    def strategy_group(self, name):
        return self._strategies[name]

    def fused_strategy_group(self, name):
        return self._fused[name]

    def rank_in_strategy(self, name):
        import paddle_tpu.distributed as dist
        g = self._strategies[name].group
        ranks = getattr(g, "ranks", None)
        return ranks.index(dist.get_rank()) if ranks else 0
