"""Fleet datasets (reference: python/paddle/distributed/fleet/dataset/
dataset.py — InMemoryDataset, QueueDataset).

The reference streams slot-formatted text through C++ DataFeed workers.
Here the same API fronts a host-side loader: a filelist of text files
(one sample per line, fields parsed by `parse_fn`, default
whitespace-separated floats), batched for the training loop.
InMemoryDataset materializes + shuffles in RAM; QueueDataset streams
lazily.  Multi-worker file sharding follows the PS convention
(round-robin by worker index).
"""
from __future__ import annotations

import random

import numpy as np

__all__ = ["InMemoryDataset", "QueueDataset"]


def _default_parse(line):
    return np.asarray([float(x) for x in line.split()], np.float32)


class _DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._use_vars = []
        self._parse_fn = _default_parse
        self._shard_num = 1
        self._shard_id = 0

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             input_type=0, fs_name="", fs_ugi="", parse_fn=None, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_vars = list(use_var or [])
        if parse_fn is not None:
            self._parse_fn = parse_fn
        return self

    # reference names kept
    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_use_var(self, use_vars):
        self._use_vars = list(use_vars)

    def set_parse_fn(self, fn):
        self._parse_fn = fn

    def _shard(self, num, idx):
        """PS convention: worker idx reads files [idx::num]."""
        self._shard_num = num
        self._shard_id = idx

    def _my_files(self):
        return self._filelist[self._shard_id::self._shard_num]

    def _read_files(self):
        for path in self._my_files():
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        yield self._parse_fn(line)

    @staticmethod
    def _batched(it, batch_size, drop_last=False):
        buf = []
        for sample in it:
            buf.append(sample)
            if len(buf) == batch_size:
                yield np.stack(buf)
                buf = []
        if buf and not drop_last:
            yield np.stack(buf)


class InMemoryDataset(_DatasetBase):
    """Load the shard into host RAM, shuffle, iterate batches
    (reference dataset.py InMemoryDataset)."""

    def __init__(self):
        super().__init__()
        self._samples = None

    def load_into_memory(self):
        self._samples = list(self._read_files())

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        if self._samples is None:
            self.load_into_memory()

    def local_shuffle(self, seed=None):
        self._require_loaded()
        random.Random(seed).shuffle(self._samples)

    def global_shuffle(self, fleet=None, thread_num=None, seed=None):
        # single-controller: the global set IS the local set
        self.local_shuffle(seed)

    def get_memory_data_size(self, fleet=None):
        self._require_loaded()
        return len(self._samples)

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size()

    def release_memory(self):
        self._samples = None

    def _require_loaded(self):
        if self._samples is None:
            raise RuntimeError("call load_into_memory() first")

    def __iter__(self):
        self._require_loaded()
        return self._batched(iter(self._samples), self._batch_size)


class QueueDataset(_DatasetBase):
    """Streaming dataset: files are read lazily on iteration, nothing is
    materialized (reference dataset.py QueueDataset)."""

    def __iter__(self):
        return self._batched(self._read_files(), self._batch_size)
