"""Failure detection / elastic supervision.

Reference parity: python/paddle/distributed/elastic (+ fleet elastic
manager): etcd-backed node watchdogs that detect dead trainers and
trigger job restart. TPU-native design: JAX is single-controller per host,
so in-process failure detection is (a) a step-progress watchdog (training
stall = hung collective / wedged device — the moral equivalent of a NCCL
timeout) and (b) multi-host liveness via the jax.distributed coordination
service, which already evicts dead hosts at barrier timeout. The watchdog
runs as a daemon thread; on stall it snapshots live stacks (for the bug
report) and invokes the user callback (default: log + optional abort).
"""
from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time


class Watchdog:
    """Step-progress heartbeat. Call beat() every train step; if no beat
    arrives within `timeout` seconds the stall callback fires (once per
    stall episode).

    Usage:
        wd = Watchdog(timeout=300, abort=True)
        for batch in loader:
            train_step(batch)
            wd.beat(step)
        wd.stop()
    """

    def __init__(self, timeout=300.0, on_stall=None, abort=False,
                 poll_interval=None):
        self.timeout = float(timeout)
        self.on_stall = on_stall
        self.abort = abort
        self._poll = poll_interval or min(self.timeout / 4, 10.0)
        self._last_beat = time.monotonic()
        self._last_step = None
        self._stalled = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle_tpu-watchdog")
        self._thread.start()

    def beat(self, step=None):
        self._last_beat = time.monotonic()
        self._last_step = step
        self._stalled = False

    def _run(self):
        while not self._stop.wait(self._poll):
            idle = time.monotonic() - self._last_beat
            if idle > self.timeout and not self._stalled:
                self._stalled = True
                self._fire(idle)

    def _fire(self, idle):
        msg = (f"[paddle_tpu.elastic] WATCHDOG: no training progress for "
               f"{idle:.0f}s (last step {self._last_step}); likely a hung "
               f"collective or wedged device")
        print(msg, file=sys.stderr, flush=True)
        try:
            faulthandler.dump_traceback(file=sys.stderr)  # live stacks
        except Exception:
            pass
        if self.on_stall is not None:
            try:
                self.on_stall(idle, self._last_step)
            except Exception:
                pass
        if self.abort:
            os._exit(43)  # distinct exit code: watchdog kill -> relaunch

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def _hb_prefix():
    """Heartbeat keys live under the run's coordination namespace
    (protolint PL101): un-namespaced ``ptpu/hb/*`` keys survive the
    end-of-run namespace reap on a long-lived coordinator, so the
    NEXT launch's rank 0 reads this run's final beats as fresh-enough
    liveness and delays dead-host detection by a full grace period."""
    from paddle_tpu.resilience import fleet
    return f"{fleet.coord_namespace()}/hb"


class HeartbeatServer:
    """Multi-host liveness over the jax.distributed KV store: every host
    publishes a timestamp; rank 0 flags hosts whose heartbeat is stale.
    Degrades to a no-op in single-process runs.

    Keys are run-namespaced (:func:`_hb_prefix`) and each host reaps
    its own key in :meth:`stop`, so a clean shutdown leaves nothing in
    the store and a SIGKILLed host's key still dies with the
    namespace reap."""

    def __init__(self, interval=30.0, stale_after=120.0, on_dead=None,
                 client=None):
        self.interval = interval
        self.stale_after = stale_after
        self.on_dead = on_dead
        self._client = client
        self._stop = threading.Event()
        self._start_time = time.time()
        self._pid = None
        if self._client is None:
            try:
                from jax._src.distributed import global_state
                self._client = global_state.client
            except Exception:
                self._client = None
        self._thread = None
        if self._client is not None:
            # publish-then-spawn: the beat loop and stop() both read
            # _pid, so it must be set before the thread starts
            import jax
            self._pid = jax.process_index()
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        import jax
        pid = self._pid
        nproc = jax.process_count()
        consecutive_failures = 0
        while not self._stop.wait(self.interval):
            now = str(time.time())
            try:
                prefix = _hb_prefix()
                # fixed key per rank (overwritten each beat) — O(nranks)
                # store size, not O(beats)
                try:
                    self._client.key_value_set(f"{prefix}/{pid}", now,
                                               allow_overwrite=True)
                except TypeError:  # older client without the kwarg
                    self._client.key_value_delete(f"{prefix}/{pid}")
                    self._client.key_value_set(f"{prefix}/{pid}", now)
                if pid == 0:
                    dirs = self._client.key_value_dir_get(
                        f"{_hb_prefix()}/")
                    latest = {}
                    for k, v in dirs:
                        r = int(k.rsplit("/", 1)[-1])
                        latest[r] = max(latest.get(r, 0.0), float(v))
                    cutoff = time.time() - self.stale_after
                    # a rank with NO heartbeat yet is only "dead" after the
                    # startup grace period — else slow-starting hosts get
                    # flagged (and possibly restarted) on rank 0's first poll
                    grace_over = time.time() - self._start_time > \
                        self.stale_after
                    dead = [r for r in range(nproc)
                            if (latest[r] < cutoff if r in latest
                                else grace_over)]
                    if dead and self.on_dead is not None:
                        self.on_dead(dead)
                consecutive_failures = 0
            except Exception as e:
                # a silently-dead heartbeat loop would disable dead-host
                # detection with no trace; log (rate-limited) and give up
                # loudly after repeated failures so operators can see it
                consecutive_failures += 1
                if consecutive_failures <= 3 or \
                        consecutive_failures % 20 == 0:
                    print(f"[paddle_tpu.elastic] heartbeat poll failed "
                          f"({consecutive_failures}x): {type(e).__name__}: "
                          f"{e}", file=sys.stderr, flush=True)
                if consecutive_failures >= 60:
                    print("[paddle_tpu.elastic] heartbeat DISABLED after "
                          "60 consecutive failures — liveness monitoring "
                          "is NOT functioning", file=sys.stderr, flush=True)
                    return

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # a beat in flight after the delete below would resurrect
            # the key; wait the loop out first
            self._thread.join(timeout=5)
        if self._client is not None and self._pid is not None:
            try:
                self._client.key_value_delete(f"{_hb_prefix()}/{self._pid}")
            except Exception:
                pass


class ElasticManager:
    """Reference: fleet elastic manager — here a thin supervisor combining
    the step watchdog with host heartbeats, and (optionally) a
    resilience PreemptionHandler so drains and heartbeats compose: the
    handler's drain calls :func:`notify_progress` around its final
    checkpoint write, which beats THIS manager's watchdog — a slow
    final save is progress, not a stall."""

    def __init__(self, timeout=300.0, abort_on_stall=True,
                 preemption=None):
        self.watchdog = Watchdog(timeout=timeout, abort=abort_on_stall)
        self.heartbeats = HeartbeatServer()
        self.preemption = preemption
        if preemption is not None:
            from paddle_tpu.resilience import preemption as _pre
            _pre.install(preemption)
            preemption.install_signal_handlers()

    def beat(self, step=None):
        self.watchdog.beat(step)

    def stop(self):
        self.watchdog.stop()
        self.heartbeats.stop()
        if self.preemption is not None:
            self.preemption.uninstall_signal_handlers()
            # and the process-global registration (symmetric with
            # __init__): a stopped manager's handler must not swallow
            # later request_preemption() calls — no loop polls it
            from paddle_tpu.resilience import preemption as _pre
            _pre.uninstall(self.preemption)


# ---- global progress hook ------------------------------------------------
# The launch CLI installs a manager here; Optimizer.step() calls
# notify_progress() so a watchdog started by the launcher sees heartbeats
# WITHOUT the training script knowing about it (otherwise a CLI-configured
# watchdog would fire on perfectly healthy runs).
_active_manager = None
_step_counter = [0]


def install_manager(manager):
    global _active_manager
    _active_manager = manager
    return manager


def get_manager():
    return _active_manager


def notify_progress():
    if _active_manager is not None:
        _step_counter[0] += 1
        _active_manager.beat(_step_counter[0])
    # every watchdog beat is ALSO fleet progress: the rank heartbeat
    # publisher's progress counter advances per microbatch (e.g. each
    # GradientMergeOptimizer accumulate step), so a slow k-step
    # accumulate window — where Optimizer.step never fires — cannot be
    # misclassified SUSPECT by a progress-aware FleetMonitor
    from paddle_tpu.resilience import fleet
    fleet.notify_fleet_progress()


class Command:
    """Elastic scale control (reference distributed/elastic.py:19): the
    reference stores the target world size np in etcd. Zero external
    services here — the KV is a local JSON file shared by node-local
    processes (cross-host coordination is jax.distributed's job)."""

    def __init__(self, server=None, name="default"):
        import json
        import os
        import tempfile
        self._json = json
        self.path = os.path.join(tempfile.gettempdir(),
                                 f"ptpu_elastic_{name}.json")

    def _read(self):
        import os
        if not os.path.exists(self.path):
            return {}
        try:
            with open(self.path) as fh:
                return self._json.load(fh)
        except Exception:
            return {}

    def set_np(self, np):
        state = self._read()
        state["np"] = int(np)
        with open(self.path, "w") as fh:
            self._json.dump(state, fh)

    def scale_np(self, np):
        if self._read().get("np") is not None:
            self.set_np(np)
            return True
        return False

    def clean(self):
        import os
        if os.path.exists(self.path):
            os.remove(self.path)
