"""Activation recomputation. Reference: python/paddle/distributed/fleet/recompute/.

TPU-native: `jax.checkpoint` (rematerialization) — XLA recomputes the segment
in the backward pass, trading FLOPs for HBM. The wrapped Layer's parameters
are lifted to explicit arguments of the checkpointed function (temporarily
re-bound during the inner run) so parameter gradients flow through the
rematerialized region in both eager-tape and to_static modes.
"""
from __future__ import annotations

import threading

import jax

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

_rc_tls = threading.local()


def recompute_active():
    """True while a recompute region's forward (or backward re-run) is
    executing on this thread — the guard ``Layer.__call__`` uses so a
    per-Layer ``enable_recompute`` can wrap through ``recompute(self,
    ...)`` without recursing, and nested remat layers are not
    re-wrapped (the outermost region wins)."""
    return getattr(_rc_tls, "depth", 0) > 0


def _owner_layer(function):
    from paddle_tpu.nn.layer.layers import Layer
    if isinstance(function, Layer):
        return function
    self_obj = getattr(function, "__self__", None)
    if isinstance(self_obj, Layer):
        return self_obj
    return None


def recompute(function, *args, **kwargs):
    kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)

    layer = _owner_layer(function)
    params = list(layer.parameters()) if layer is not None else []
    buffers = list(layer.buffers()) if layer is not None else []
    # the global RNG key threads through like a buffer: stochastic ops
    # (dropout) inside the checkpointed region draw sub-trace keys, and
    # (a) the advanced key must ESCAPE as a checkpoint output (a bare
    # mutation would leak a sub-trace tracer into the ambient state),
    # (b) the backward rematerialization re-enters with the SAME key, so
    # the recomputed dropout mask matches the forward's exactly
    from paddle_tpu.framework.state import _key_tensor
    buffers = buffers + [_key_tensor()]
    state = params + buffers
    n_args = len(args)
    arg_is_tensor = [isinstance(a, Tensor) for a in args]
    tensor_args = [a for a in args if isinstance(a, Tensor)]

    meta = {"n_user": 1, "is_seq": False}

    # VJP-only rematerialization (NOT jax.checkpoint): the eager tape
    # pre-lowers every op's custom_vjp into raw fwd/bwd calls, so by the
    # time jax.checkpoint would linearize this region via JVP the flash
    # attention pallas_call appears raw — and pallas has no usable JVP
    # rule (AssertionError in _pallas_call_jvp_rule; found the first
    # time recompute wrapped flash ON TPU). A custom_vjp whose backward
    # re-executes the forward needs no JVP anywhere: fwd saves ONLY the
    # inputs, bwd re-runs the region (that re-trace IS the remat) and
    # pulls the cotangent through it.
    def inner(arg_vals, state_vals):
        saved = [(t._value, t._version, t._node, t.stop_gradient) for t in state]
        _rc_tls.depth = getattr(_rc_tls, "depth", 0) + 1
        try:
            for t, v in zip(state, state_vals):
                t._value = v
                t._node = None
            it = iter(arg_vals)
            call_args = []
            for i in range(n_args):
                if arg_is_tensor[i]:
                    nt = Tensor(next(it))
                    nt.stop_gradient = False
                    call_args.append(nt)
                else:
                    call_args.append(args[i])
            out = function(*call_args, **kwargs)
            if isinstance(out, (tuple, list)):
                meta["is_seq"] = True
                outs = tuple(o._value if isinstance(o, Tensor) else o
                             for o in out)
            else:
                meta["is_seq"] = False
                outs = (out._value if isinstance(out, Tensor) else out,)
            meta["n_user"] = len(outs)
            # buffer updates (BN running stats …) must ESCAPE the
            # checkpointed region: the finally below restores every
            # state tensor, so thread the post-run buffer values out as
            # extra outputs and reapply them outside
            new_buf = tuple(t._value for t in buffers)
            return outs + new_buf
        finally:
            _rc_tls.depth -= 1
            for t, (v, ver, node, sg) in zip(state, saved):
                t._value = v
                t._version = ver
                t._node = node
                t.stop_gradient = sg

    @jax.custom_vjp
    def ckpt(arg_vals, state_vals):
        return inner(arg_vals, state_vals)

    def ckpt_fwd(arg_vals, state_vals):
        # residuals = the region's INPUTS only — the jax.checkpoint
        # memory contract.  Under an amp remat="bf16" policy the saved
        # ACTIVATION boundaries narrow to bf16 (the only live copies of
        # the residual stream between forward and backward are then
        # half-size); lifted params/buffers are never narrowed — they
        # are the master weights.
        from paddle_tpu.amp.policy import current_policy
        pol = current_policy()
        saved_args = arg_vals
        if pol is not None and pol.remat == "bf16":
            saved_args = [pol.cast_saved(v) for v in arg_vals]
        # scalar zero protos carry the primal dtypes to the bwd rule
        # (residual leaves must be jax values, not dtype objects)
        protos = [jax.numpy.zeros((), v.dtype) for v in arg_vals]
        return inner(arg_vals, state_vals), \
            (saved_args, state_vals, protos)

    def ckpt_bwd(res, ct):
        saved_args, state_vals, protos = res
        # bf16-saved boundaries are cast back up before the re-run so
        # the rematerialized region (and its cotangent structure)
        # matches the forward's dtypes exactly — the precision loss is
        # confined to the saved boundary value's bf16 round-trip
        arg_vals = [v.astype(p.dtype) if v.dtype != p.dtype else v
                    for v, p in zip(saved_args, protos)]
        # barrier: without it XLA CSEs the re-run against the forward's
        # values and silently un-remats the region
        arg_vals, state_vals = jax.lax.optimization_barrier(
            (arg_vals, state_vals))
        _, pull = jax.vjp(inner, arg_vals, state_vals)
        return pull(ct)

    ckpt.defvjp(ckpt_fwd, ckpt_bwd)

    def fn(*vals):
        avals = list(vals[:len(tensor_args)])
        svals = list(vals[len(tensor_args):])
        return ckpt(avals, svals)

    result = apply(fn, *tensor_args, *state)
    result = result if isinstance(result, tuple) else (result,)
    user = result[:meta["n_user"]]
    for t, new in zip(buffers, result[meta["n_user"]:]):
        t._set_value(new._value)
    if not meta["is_seq"]:
        return user[0]
    return tuple(user)


class _SegmentChain:
    """Callable chunk of a Sequential whose parameters recompute() can
    lift: registers every member Layer so _owner_layer finds them all."""

    def __init__(self, fns):
        from paddle_tpu.nn.layer.layers import Layer
        self._holder = Layer()
        self._fns = list(fns)
        for i, f in enumerate(self._fns):
            # a member may be a Layer OR a bound method of one — lift
            # the owner either way, else its params silently lose grads
            owner = _owner_layer(f)
            if owner is not None:
                self._holder.add_sublayer(str(i), owner)
        # recompute() lifts params via function.__self__
        self.__self__ = self._holder

    def __call__(self, *args, **kwargs):
        # first member takes the user's full signature; the rest chain
        # on its (single) output like the reference's do_run
        x = self._fns[0](*args, **kwargs)
        for f in self._fns[1:]:
            x = f(x)
        return x


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Chunk a Sequential into ctx['segments'] recompute regions
    (reference fleet/recompute/recompute.py:512). Each chunk is wrapped
    so ALL member layers' parameters lift into the checkpointed region
    — a bare closure would silently drop their gradients."""
    ctx = dict(ctx or {})
    segments = max(int(ctx.get("segments", 1)), 1)
    from paddle_tpu.nn.layer.container import Sequential
    if isinstance(functions, Sequential):
        functions = [m for _, m in functions.named_children()]
    functions = list(functions)
    seg = max(len(functions) // segments, 1)
    out = args
    pos = 0
    while pos < len(functions):
        end = min(pos + seg, len(functions))
        if len(functions) - end < seg:
            end = len(functions)
        chain = _SegmentChain(functions[pos:end])
        out = recompute(chain, *(out if isinstance(out, tuple)
                                 else (out,)), **kwargs)
        pos = end
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Hybrid-parallel recompute (reference recompute_hybrid.py:234):
    the ctx's mp_group/offload/partition keys configure hand-partitioned
    activation storage there; under XLA rematerialized values keep their
    producers' shardings, so this reduces to recompute."""
    kwargs.pop("preserve_rng_state", None)
    return recompute(function, *args, **kwargs)
