"""Quantized all-reduce — gradient sync at int8 wire width.

Motivated by EQuARX (Efficient Quantized AllReduce in XLA,
arXiv:2506.17615, see PAPERS.md): data-parallel gradient all-reduce is
ICI-bandwidth-bound, and int8 payloads quadruple the effective link
bandwidth at a bounded quantization error. The reference framework's
analogue is fleet's fp16/bf16 gradient compression knobs
(DistributedStrategy fp16_allreduce).

TPU-native rendering (call INSIDE shard_map over the reduce axis):
1. global per-tensor scale: pmax of the local absmax over the axis —
   every rank quantizes against the SAME scale, so the integer sum is
   exact (no per-rank rescaling error);
2. stochastic rounding (engaged by passing a step-varying `key`, e.g.
   folded from the training step's RNG) keeps the rounding error
   unbiased and decorrelated over the trajectory; without a key the
   rounding is deterministic round-to-nearest (a FIXED key would round
   each value the same way every step — systematic error with none of
   the benefit, so that is not a default);
3. psum runs on int32 (int8 values sum without overflow for any
   realistic axis size: 127 * n_ranks << 2^31);
4. dequantize by scale / n is the mean.

The wire format is what XLA's collective sees: an int32 tensor whose
values fit in 9-ish bits — with EQuARX-class compiler support the
transfer runs at the narrow width; without it, correctness and the
API are unchanged (the compiler may still pack). `bits` trades error
for headroom (8 default).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["quantized_all_reduce_mean", "quantized_all_reduce_sum"]


def _quantize(x, scale, qmax, key):
    xs = x.astype(jnp.float32) / jnp.maximum(scale, 1e-30) * qmax
    # the rounding/clip core (incl. stochastic floor+Bernoulli) is the
    # ONE shared definition in quantization.kv_cache — int32 here
    # because this legacy wire format psums the codes directly
    from paddle_tpu.quantization.kv_cache import encode_int_codes
    return encode_int_codes(xs, qmax, key, dtype=jnp.int32)


def quantized_all_reduce_sum(x, axis_name="dp", bits=8, key=None):
    """Sum `x` over `axis_name` with an int-quantized payload.

    x: local float array (any shape). Returns float32 of x's shape.
    key: optional PRNG key enabling stochastic rounding — pass a
    STEP-VARYING key (it is folded with the rank index here) so the
    rounding error is unbiased over the trajectory.
    """
    qmax = float(2 ** (bits - 1) - 1)
    # one global scale so every rank's integer grid aligns and the
    # integer psum is EXACT given the quantized inputs
    scale = lax.pmax(jnp.max(jnp.abs(x.astype(jnp.float32))), axis_name)
    if key is not None:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    q = _quantize(x, scale, qmax, key)
    total = lax.psum(q, axis_name)
    return total.astype(jnp.float32) * (scale / qmax)


def quantized_all_reduce_mean(x, axis_name="dp", bits=8, key=None):
    """Mean over `axis_name` (the dp gradient-sync op) at int wire width."""
    n = lax.psum(jnp.ones((), jnp.float32), axis_name)
    return quantized_all_reduce_sum(x, axis_name, bits, key) / n
