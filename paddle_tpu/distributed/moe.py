"""Mixture-of-Experts with expert parallelism — TPU-native.

Reference parity:
  python/paddle/incubate/distributed/models/moe/moe_layer.py (MoELayer),
  .../moe/gate/{naive_gate,gshard_gate,switch_gate}.py,
  python/paddle/distributed/utils/moe_utils.py (global_scatter/global_gather).

The reference is FastMoE-style: data-dependent scatter of tokens into
per-expert buffers, NCCL all-to-all of ragged counts, per-expert Linear
loops.  None of that maps to XLA: data-dependent shapes don't compile, and
ragged buffers defeat the MXU.  The TPU-native design is the GShard/Switch
formulation: every routing decision becomes a STATIC-shape one-hot
``dispatch`` mask [tokens, experts, capacity] and a differentiable
``combine`` tensor of gate weights; dispatch/combine are einsums (MXU
work), tokens over capacity are dropped (the residual connection carries
them), and expert parallelism is a sharding annotation on the expert axis
of the [E, C, d] dispatched activations — XLA's partitioner inserts the
same all-to-all the reference issues by hand through NCCL.
"""
from __future__ import annotations

import math

import paddle_tpu
from paddle_tpu import nn
from paddle_tpu.core.dispatch import apply
from paddle_tpu.distributed.fleet.meta_parallel import _constrain
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I

__all__ = [
    "BaseGate", "NaiveGate", "GShardGate", "SwitchGate",
    "MoELayer", "StackedExpertFFN", "dispatch_combine",
]


def _capacity(num_tokens, num_experts, top_k, capacity_factor):
    """GShard per-expert capacity: each expert can take its fair share of
    the top_k routed tokens, scaled by the capacity factor."""
    return max(1, math.ceil(capacity_factor * num_tokens * top_k
                            / num_experts))


def dispatch_combine(probs, top_k, capacity, keep_last=None):
    """Static-shape GShard routing tensors from router probabilities.

    probs: [n, E] router probabilities (post-softmax, differentiable).
    keep_last: optional [n] 0/1 mask gating each token's LAST (lowest-
    priority) expert choice — the hook for GShard's stochastic
    second-expert routing.
    Returns (combine [n, E, C], dispatch [n, E, C]) where dispatch is the
    0/1 routing mask (top_k choices, position-in-expert < capacity, GShard
    priority: all top-1 picks claim capacity before any top-2 pick) and
    combine carries the gate weights at the same positions.  Both are
    differentiable in `probs` through the top-k gate values.
    """
    def fn(p, *rest):
        return gshard_dispatch_combine(p, top_k, capacity,
                                       rest[0] if rest else None)

    if keep_last is not None:
        return apply(fn, probs, keep_last)
    return apply(fn, probs)


def gshard_dispatch_combine(p, top_k, capacity, kl=None):
    """Plain-jnp GShard routing core shared by the nn MoELayer and the
    explicit hybrid (models/gpt_hybrid._moe_ffn). p: [n, E] probs."""
    import jax
    import jax.numpy as jnp

    n, e = p.shape
    vals, idx = jax.lax.top_k(p, top_k)            # [n, K]
    onehot = jax.nn.one_hot(idx, e, dtype=p.dtype)  # [n, K, E]
    if kl is not None:
        onehot = onehot.at[:, top_k - 1, :].multiply(
            kl.astype(p.dtype)[:, None])
    # rank of each token within its chosen expert; top-1 column fills
    # before top-2 (GShard §3.2) so the primary route wins capacity
    offset = jnp.zeros((e,), p.dtype)
    keep_k, pos_k = [], []
    for k in range(top_k):
        mk = onehot[:, k, :]                        # [n, E]
        pos = jnp.cumsum(mk, axis=0) - mk + offset  # [n, E]
        offset = offset + mk.sum(axis=0)
        keep_k.append(mk * (pos < capacity))
        pos_k.append(pos)
    keep = jnp.stack(keep_k, 1)                     # [n, K, E]
    pos = jnp.stack(pos_k, 1)                       # [n, K, E]
    slot = jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity,
        dtype=p.dtype)                              # [n, K, E, C]
    disp_k = keep[..., None] * slot                 # [n, K, E, C]
    dispatch = disp_k.sum(axis=1)
    combine = (vals[:, :, None, None] * disp_k).sum(axis=1)
    return combine, dispatch


class BaseGate(nn.Layer):
    """Reference-API base: gates stash their auxiliary (load-balancing)
    loss; the training loop reads it via get_loss() and adds it to the
    task loss (reference moe/gate/base_gate.py)."""

    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = num_expert * world_size
        self.loss = None

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Linear router + top-k softmax over the selected experts
    (reference moe/gate/naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.top_k = topk
        self.gate = nn.Linear(
            d_model, self.tot_expert,
            weight_attr=I.ParamAttr(initializer=I.Normal(0.0, 0.02)))

    def scores(self, x):
        """Full softmax router probabilities [n, E] (differentiable)."""
        return F.softmax(self.gate(x), axis=-1)

    def forward(self, x, return_all_scores=False):
        logits = self.gate(x)
        vals, idx = paddle_tpu.topk(logits, self.top_k, axis=-1)
        vals = F.softmax(vals, axis=-1)
        if return_all_scores:
            return vals, idx, logits
        return vals, idx


class GShardGate(NaiveGate):
    """Top-2 gate with the GShard load-balancing auxiliary loss
    mean(c_e * m_e) * E^2 (reference moe/gate/gshard_gate.py) and optional
    stochastic second-expert routing."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        assert topk == 2, "topk should be 2 in gshard"
        super().__init__(d_model, num_expert, world_size, topk=topk)
        self.capacity_factor = capacity
        self.random_routing = random_routing

    def aux_loss(self, probs, top1_idx):
        c_e = F.one_hot(top1_idx, self.tot_expert).mean(axis=0)
        m_e = probs.mean(axis=0)
        loss = (c_e * m_e).mean() * (self.tot_expert ** 2)
        self.set_loss(loss)
        return loss


class SwitchGate(NaiveGate):
    """Top-1 gate with multiplicative jitter noise in training and the
    Switch-Transformer balance loss sum(f_e * p_e) * E
    (reference moe/gate/switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        assert topk == 1, "topk should be 1 in switch"
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps
        self.capacity_factor = capacity

    def scores(self, x):
        logits = self.gate(x)
        if self.training and self.switch_eps:
            noise = paddle_tpu.rand(logits.shape, dtype="float32")
            logits = logits * (
                noise * (2 * self.switch_eps) + (1.0 - self.switch_eps))
        return F.softmax(logits, axis=-1)

    def aux_loss(self, probs, top1_idx):
        f_e = F.one_hot(top1_idx, self.tot_expert).mean(axis=0)
        p_e = probs.mean(axis=0)
        loss = (f_e * p_e).sum() * self.tot_expert
        self.set_loss(loss)
        return loss


class StackedExpertFFN(nn.Layer):
    """All experts' FFN weights stacked on a leading expert axis so the
    expert compute is ONE batched einsum over [E, C, d] — the MXU-friendly
    replacement for the reference's Python loop over per-expert Linears.
    Weights are annotated to shard over the `ep` mesh axis."""

    def __init__(self, num_experts, d_model, d_hidden, ep_axis="ep",
                 activation="gelu"):
        super().__init__()
        from paddle_tpu.distributed.mesh import shard_tensor
        self.num_experts = num_experts
        self.ep_axis = ep_axis
        self.activation = activation
        init = I.Normal(0.0, 0.02)
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden],
                                        default_initializer=init)
        self.b1 = self.create_parameter(
            [num_experts, d_hidden], default_initializer=I.Constant(0.0))
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model],
                                        default_initializer=init)
        self.b2 = self.create_parameter(
            [num_experts, d_model], default_initializer=I.Constant(0.0))
        for w in (self.w1, self.b1, self.w2, self.b2):
            shard_tensor(w, ep_axis)

    def forward(self, x):
        # x: [E, C, d] dispatched tokens, expert axis sharded over ep
        h = paddle_tpu.einsum("ecd,edh->ech", x, self.w1) + self.b1.unsqueeze(1)
        h = F.gelu(h, approximate=True) if self.activation == "gelu" \
            else F.relu(h)
        return paddle_tpu.einsum("ech,ehd->ecd", h, self.w2) \
            + self.b2.unsqueeze(1)


class MoELayer(nn.Layer):
    """Mixture-of-experts layer (reference moe_layer.py MoELayer).

    Args mirror the reference: `experts` is either a LayerList of
    per-expert Layers ([C, d] -> [C, d]) or a StackedExpertFFN; `gate` a
    dict config ({"type": "gshard"|"switch"|"naive", "top_k": k}) or a
    BaseGate instance.  `moe_group`/`mp_group` become the `ep_axis` mesh
    axis name — the reference's process groups are mesh axes here, and the
    all-to-all the reference issues through NCCL (global_scatter /
    global_gather) is inserted by the XLA partitioner from the sharding
    constraint on the dispatched [E, C, d] activations.

    Tokens routed beyond an expert's capacity contribute zero output (the
    surrounding residual carries them) — identical semantics to the
    reference's capacity-limited gates.
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, ep_axis="ep", capacity_factor=(1.2, 2.4),
                 recompute_interval=0, recompute_ctx=None):
        super().__init__()
        self.d_model = d_model
        self.ep_axis = ep_axis if moe_group is None else moe_group
        if isinstance(experts, StackedExpertFFN):
            self.experts = experts
            self.num_expert = experts.num_experts
        else:
            self.experts = nn.LayerList(list(experts))
            self.num_expert = len(self.experts)

        if gate is None or isinstance(gate, dict):
            gate = dict(gate or {})
            top_k = gate.get("top_k", 2)
            kind = gate.get("type", "gshard")
            if kind in (None, "naive"):
                gate = NaiveGate(d_model, self.num_expert, topk=top_k)
            elif kind == "gshard":
                gate = GShardGate(d_model, self.num_expert, topk=top_k,
                                  capacity=capacity_factor)
            elif kind == "switch":
                gate = SwitchGate(d_model, self.num_expert,
                                  capacity=capacity_factor)
            else:
                raise ValueError(f"unknown gate type {kind!r}")
        elif not isinstance(gate, BaseGate):
            raise TypeError("gate must be a dict config or a BaseGate")
        self.gate = gate
        self.top_k = gate.top_k
        self.capacity_factor = getattr(gate, "capacity_factor",
                                       capacity_factor)

    def _run_experts(self, xin):
        if isinstance(self.experts, StackedExpertFFN):
            return self.experts(xin)
        outs = [self.experts[e](xin[e]) for e in range(self.num_expert)]
        return paddle_tpu.stack(outs, axis=0)

    def forward(self, x):
        orig_shape = x.shape
        n = 1
        for s in orig_shape[:-1]:
            n *= s
        xf = x.reshape([n, self.d_model])

        probs = self.gate.scores(xf)                       # [n, E]
        _, top_idx = paddle_tpu.topk(probs, self.top_k, axis=-1)
        if hasattr(self.gate, "aux_loss"):
            self.gate.aux_loss(probs, top_idx[:, 0])

        cap_rate = self.capacity_factor[0 if self.training else 1]
        capacity = _capacity(n, self.num_expert, self.top_k, cap_rate)
        # GShard stochastic second-expert routing (reference
        # moe/utils.py _random_routing): keep the 2nd choice with
        # probability min(1, 2 * its gate value)
        keep_last = None
        if (self.training and self.top_k == 2
                and getattr(self.gate, "random_routing", False)):
            vals2, _ = paddle_tpu.topk(probs, 2, axis=-1)
            r = paddle_tpu.rand([n], dtype="float32")
            keep_last = (vals2[:, 1] * 2.0 > r).astype("float32")
        combine, dispatch = dispatch_combine(probs, self.top_k, capacity,
                                             keep_last=keep_last)

        xin = paddle_tpu.einsum("nec,nd->ecd", dispatch, xf)
        xin = _constrain(xin, self.ep_axis, None, None)
        out = self._run_experts(xin)                       # [E, C, d]
        out = _constrain(out, self.ep_axis, None, None)
        y = paddle_tpu.einsum("nec,ecd->nd", combine, out)
        return y.reshape(orig_shape)
