from . import moe_utils  # noqa: F401
from .moe_utils import global_gather, global_scatter  # noqa: F401
from .launch_utils import (  # noqa: F401
    Cluster,
    Hdfs,
    JobServer,
    Pod,
    Trainer,
    TrainerProc,
    add_arguments,
    find_free_ports,
    get_cluster,
    get_cluster_from_args,
    get_gpus,
    get_host_name_ip,
    get_logger,
    pull_worker_log,
    start_local_trainers,
    terminate_local_procs,
    watch_local_trainers,
)
