"""Launch topology dataclasses + process helpers (reference:
python/paddle/distributed/utils/launch_utils.py — Hdfs :102,
Cluster :131, JobServer :197, Trainer :211, Pod :242, get_cluster :305,
terminate_local_procs :332, add_arguments :368, find_free_ports :386,
TrainerProc :457).

These model the multi-host job layout that paddle_tpu.distributed.launch
drives; "gpus" become TPU-chip ordinals, everything else carries over.
"""
from __future__ import annotations

import os
import signal
import socket
import time

__all__ = ["Hdfs", "Cluster", "JobServer", "Trainer", "Pod", "TrainerProc",
           "get_cluster", "get_cluster_from_args", "terminate_local_procs",
           "get_host_name_ip", "add_arguments", "find_free_ports",
           "get_logger"]


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return bool(self.hdfs_ugi and self.hdfs_name and self.hdfs_path)

    def __eq__(self, other):
        return (self.hdfs_ugi == other.hdfs_ugi
                and self.hdfs_name == other.hdfs_name
                and self.hdfs_path == other.hdfs_path)

    def __ne__(self, other):
        return not self == other

    def __str__(self):
        return f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} " \
               f"hdfs_path:{self.hdfs_path}"


class JobServer:
    def __init__(self):
        self.endpoint = None

    def __str__(self):
        return f"{self.endpoint}"

    def __eq__(self, other):
        return self.endpoint == other.endpoint

    def __ne__(self, other):
        return not self == other


class Trainer:
    def __init__(self):
        self.gpus = []      # chip ordinals on this pod
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return f"gpu:{self.gpus} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, other):
        return (self.gpus == other.gpus and self.endpoint == other.endpoint
                and self.rank == other.rank)

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []
        self.gpus = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} visible_gpu:{self.gpus} "
                f"trainers:{[str(t) for t in self.trainers]}")

    def __eq__(self, other):
        if (self.rank != other.rank or self.id != other.id
                or self.addr != other.addr or self.port != other.port
                or len(self.trainers) != len(other.trainers)):
            return False
        return all(a == b for a, b in zip(self.trainers, other.trainers))

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)

    def get_visible_gpus(self):
        return ",".join(str(g) for g in self.gpus)


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return (f"job_server:{self.job_server} "
                f"pods:{[str(p) for p in self.pods]} "
                f"job_stage_flag:{self.job_stage_flag} hdfs:{self.hdfs}")

    def __eq__(self, other):
        if len(self.pods) != len(other.pods):
            return False
        return all(a == b for a, b in zip(self.pods, other.pods))

    def __ne__(self, other):
        return not self == other

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def pod(self, rank):
        for p in self.pods:
            if p.rank == rank:
                return p
        return None


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def get_cluster(node_ips, node_ip, trainer_endpoints, selected_gpus):
    """Build the Cluster/Pod/Trainer topology (reference :305)."""
    assert isinstance(trainer_endpoints, list)
    cluster = Cluster(hdfs=None)
    trainer_rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        cur_eps = trainer_endpoints[node_rank]
        for i in range(len(selected_gpus)):
            trainer = Trainer()
            trainer.gpus.append(selected_gpus[i])
            trainer.endpoint = cur_eps[i]
            trainer.rank = trainer_rank
            trainer_rank += 1
            pod.trainers.append(trainer)
        cluster.pods.append(pod)
    pod_rank = node_ips.index(node_ip)
    return cluster, cluster.pods[pod_rank]


def get_cluster_from_args(args, selected_gpus):
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    node_ip = args.node_ip
    started_port = getattr(args, "started_port", None)
    # random free ports are only safe when every node can SEE the choice
    # — i.e. single-node with no explicit port (reference semantics);
    # multi-node must agree on started_port arithmetic
    if len(node_ips) == 1 and started_port is None:
        ports = sorted(find_free_ports(len(selected_gpus)))
    else:
        base = started_port if started_port is not None else 6170
        ports = list(range(base, base + len(selected_gpus)))
    eps = [[f"{ip}:{p}" for p in ports] for ip in node_ips]
    return get_cluster(node_ips, node_ip, eps, selected_gpus)


def terminate_local_procs(procs):
    """SIGTERM then SIGKILL stragglers (reference :332)."""
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            p.proc.terminate()
            if p.log_fn:
                try:
                    p.log_fn.close()
                except OSError:
                    pass
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(p.proc is None or p.proc.poll() is not None for p in procs):
            return
        time.sleep(0.2)
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            try:
                os.kill(p.proc.pid, signal.SIGKILL)
            except OSError:
                pass


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(host)
    except OSError:
        return None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """argparse helper preserving the reference's call shape."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: %(default)s.", **kwargs)


def find_free_ports(num):
    ports, socks = set(), []
    while len(ports) < num:
        s = socket.socket()
        s.bind(("", 0))
        socks.append(s)
        ports.add(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def get_logger(log_level=None, name="FLEET"):
    import logging
    logger = logging.getLogger(name)
    # never touch the ROOT logger's level implicitly — setLevel only on
    # an explicit request, and never for the root logger by default
    if log_level is not None:
        logger.setLevel(log_level)
    return logger


def get_gpus(selected_gpus):
    """Reference launch_utils.py:66 parses selected_gpus against
    CUDA_VISIBLE_DEVICES; the TPU analogue resolves device indices
    against TPU_VISIBLE_CHIPS (or the full local device list)."""
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible is None:
        visible = os.environ.get("CUDA_VISIBLE_DEVICES")
    # "" is an explicit ZERO-device set, distinct from unset (None)
    vis = None if visible is None else \
        [int(x) for x in visible.split(",") if x.strip() != ""]
    if selected_gpus is None:
        # relative (local) indices in BOTH branches — same index space
        # as the selected_gpus path below (reference returns
        # range(device_count) here)
        if vis is not None:
            return list(range(len(vis)))
        import jax
        return list(range(jax.local_device_count()))
    want = [int(x) for x in str(selected_gpus).split(",")]
    if vis is None:
        return want
    for w in want:
        if w not in vis:
            raise ValueError(
                f"selected device {w} not in visible set {vis}")
    # reference remaps to position within the visible list
    return [vis.index(w) for w in want]


def start_local_trainers(cluster, pod, training_script,
                         training_script_args, log_dir=None):
    """Spawn one worker process per local trainer with the jax.distributed
    bootstrap env (reference :467 sets the NCCL/gloo endpoints; here the
    coordinator/rank/world-size variables distributed.init_parallel_env
    reads)."""
    import subprocess
    import sys
    base_env = dict(os.environ)
    base_env.pop("http_proxy", None)
    base_env.pop("https_proxy", None)
    coordinator = cluster.pods_endpoints()[0]
    world = len(cluster.trainers_endpoints())
    procs = []
    for idx, t in enumerate(pod.trainers):
        env = dict(base_env)
        env.update({
            # read by distributed.init_parallel_env()'s no-arg fallback
            # and launch.py's _from_env — this is the live bootstrap path
            "PADDLE_MASTER": coordinator,
            "PADDLE_NNODES": str(world),
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_CURRENT_ENDPOINT": t.endpoint,
            "PADDLE_TRAINER_ENDPOINTS": ",".join(
                cluster.trainers_endpoints()),
            # honored by jax.distributed.initialize() autodetect
            "JAX_COORDINATOR_ADDRESS": coordinator,
        })
        cmd = [sys.executable, "-u", training_script] + list(
            training_script_args or [])
        fn = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            fn = open(os.path.join(log_dir, f"workerlog.{idx}"), "a")
            proc = subprocess.Popen(cmd, env=env, stdout=fn, stderr=fn)
        else:
            proc = subprocess.Popen(cmd, env=env)
        tp = TrainerProc()
        tp.proc = proc
        tp.rank = t.rank
        tp.local_rank = idx
        tp.log_fn = fn
        tp.log_offset = fn.tell() if fn else None
        tp.cmd = cmd
        procs.append(tp)
    return procs


def pull_worker_log(tp):
    """Stream new lines from a trainer's log file (reference :510)."""
    import sys
    if not tp.log_fn:
        return
    # errors="replace": a worker emitting non-UTF-8 bytes (progress bars,
    # locale output) must not crash the watch loop with UnicodeDecodeError
    with open(tp.log_fn.name, "r", errors="replace") as fin:
        fin.seek(tp.log_offset or 0, 0)
        for line in fin:
            try:
                sys.stdout.write(line)
            except UnicodeEncodeError:
                sys.stdout.write(f"<unwritable line; see {tp.log_fn.name}>\n")
        tp.log_offset = fin.tell()


def watch_local_trainers(procs, nranks):
    """Poll trainers: stream rank-0's log, kill the job on any nonzero
    exit, return whether any are still alive (reference :526)."""
    error, error_rank, alive = False, [], False
    for p in procs:
        if p.log_fn and p.local_rank == 0:
            pull_worker_log(p)
        ret = p.proc.poll()
        if ret is None:
            alive = True
        elif ret != 0:
            error = True
            error_rank.append(p.rank)
    if error:
        terminate_local_procs(procs)
        raise RuntimeError(
            f"local trainer ranks {error_rank} exited nonzero; job "
            "terminated")
    return alive
