"""Launch topology dataclasses + process helpers (reference:
python/paddle/distributed/utils/launch_utils.py — Hdfs :102,
Cluster :131, JobServer :197, Trainer :211, Pod :242, get_cluster :305,
terminate_local_procs :332, add_arguments :368, find_free_ports :386,
TrainerProc :457).

These model the multi-host job layout that paddle_tpu.distributed.launch
drives; "gpus" become TPU-chip ordinals, everything else carries over.
"""
from __future__ import annotations

import os
import signal
import socket
import time

__all__ = ["Hdfs", "Cluster", "JobServer", "Trainer", "Pod", "TrainerProc",
           "get_cluster", "get_cluster_from_args", "terminate_local_procs",
           "get_host_name_ip", "add_arguments", "find_free_ports",
           "get_logger"]


class Hdfs:
    def __init__(self):
        self.hdfs_ugi = None
        self.hdfs_name = None
        self.hdfs_path = None

    def is_valid(self):
        return bool(self.hdfs_ugi and self.hdfs_name and self.hdfs_path)

    def __eq__(self, other):
        return (self.hdfs_ugi == other.hdfs_ugi
                and self.hdfs_name == other.hdfs_name
                and self.hdfs_path == other.hdfs_path)

    def __ne__(self, other):
        return not self == other

    def __str__(self):
        return f"hdfs_ugi:{self.hdfs_ugi} hdfs_name:{self.hdfs_name} " \
               f"hdfs_path:{self.hdfs_path}"


class JobServer:
    def __init__(self):
        self.endpoint = None

    def __str__(self):
        return f"{self.endpoint}"

    def __eq__(self, other):
        return self.endpoint == other.endpoint

    def __ne__(self, other):
        return not self == other


class Trainer:
    def __init__(self):
        self.gpus = []      # chip ordinals on this pod
        self.endpoint = None
        self.rank = None

    def __str__(self):
        return f"gpu:{self.gpus} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, other):
        return (self.gpus == other.gpus and self.endpoint == other.endpoint
                and self.rank == other.rank)

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)


class Pod:
    def __init__(self):
        self.rank = None
        self.id = None
        self.addr = None
        self.port = None
        self.trainers = []
        self.gpus = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} "
                f"port:{self.port} visible_gpu:{self.gpus} "
                f"trainers:{[str(t) for t in self.trainers]}")

    def __eq__(self, other):
        if (self.rank != other.rank or self.id != other.id
                or self.addr != other.addr or self.port != other.port
                or len(self.trainers) != len(other.trainers)):
            return False
        return all(a == b for a, b in zip(self.trainers, other.trainers))

    def __ne__(self, other):
        return not self == other

    def rank_str(self):
        return str(self.rank)

    def get_visible_gpus(self):
        return ",".join(str(g) for g in self.gpus)


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return (f"job_server:{self.job_server} "
                f"pods:{[str(p) for p in self.pods]} "
                f"job_stage_flag:{self.job_stage_flag} hdfs:{self.hdfs}")

    def __eq__(self, other):
        if len(self.pods) != len(other.pods):
            return False
        return all(a == b for a, b in zip(self.pods, other.pods))

    def __ne__(self, other):
        return not self == other

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self):
        return len(self.trainers_endpoints())

    def pods_nranks(self):
        return len(self.pods)

    def trainers_endpoints(self):
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]

    def pod(self, rank):
        for p in self.pods:
            if p.rank == rank:
                return p
        return None


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def get_cluster(node_ips, node_ip, trainer_endpoints, selected_gpus):
    """Build the Cluster/Pod/Trainer topology (reference :305)."""
    assert isinstance(trainer_endpoints, list)
    cluster = Cluster(hdfs=None)
    trainer_rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        cur_eps = trainer_endpoints[node_rank]
        for i in range(len(selected_gpus)):
            trainer = Trainer()
            trainer.gpus.append(selected_gpus[i])
            trainer.endpoint = cur_eps[i]
            trainer.rank = trainer_rank
            trainer_rank += 1
            pod.trainers.append(trainer)
        cluster.pods.append(pod)
    pod_rank = node_ips.index(node_ip)
    return cluster, cluster.pods[pod_rank]


def get_cluster_from_args(args, selected_gpus):
    node_ips = [ip.strip() for ip in args.cluster_node_ips.split(",")]
    node_ip = args.node_ip
    started_port = getattr(args, "started_port", None)
    # random free ports are only safe when every node can SEE the choice
    # — i.e. single-node with no explicit port (reference semantics);
    # multi-node must agree on started_port arithmetic
    if len(node_ips) == 1 and started_port is None:
        ports = sorted(find_free_ports(len(selected_gpus)))
    else:
        base = started_port if started_port is not None else 6170
        ports = list(range(base, base + len(selected_gpus)))
    eps = [[f"{ip}:{p}" for p in ports] for ip in node_ips]
    return get_cluster(node_ips, node_ip, eps, selected_gpus)


def terminate_local_procs(procs):
    """SIGTERM then SIGKILL stragglers (reference :332)."""
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            p.proc.terminate()
            if p.log_fn:
                try:
                    p.log_fn.close()
                except OSError:
                    pass
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(p.proc is None or p.proc.poll() is not None for p in procs):
            return
        time.sleep(0.2)
    for p in procs:
        if p.proc is not None and p.proc.poll() is None:
            try:
                os.kill(p.proc.pid, signal.SIGKILL)
            except OSError:
                pass


def get_host_name_ip():
    try:
        host = socket.gethostname()
        return host, socket.gethostbyname(host)
    except OSError:
        return None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    """argparse helper preserving the reference's call shape."""
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + f" Default: %(default)s.", **kwargs)


def find_free_ports(num):
    ports, socks = set(), []
    while len(ports) < num:
        s = socket.socket()
        s.bind(("", 0))
        socks.append(s)
        ports.add(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def get_logger(log_level=None, name="FLEET"):
    import logging
    logger = logging.getLogger(name)
    # never touch the ROOT logger's level implicitly — setLevel only on
    # an explicit request, and never for the root logger by default
    if log_level is not None:
        logger.setLevel(log_level)
    return logger
