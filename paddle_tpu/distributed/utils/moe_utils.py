"""Expert-parallel token exchange (reference:
python/paddle/distributed/utils/moe_utils.py global_scatter/global_gather).

The reference ops move RAGGED per-expert token counts through NCCL
all-to-all; counts are runtime data.  On TPU the exchange must compile to
a static XLA `all_to_all`, so the unit of exchange is the STATIC-capacity
dispatch buffer [E, C, d] produced by GShard routing
(paddle_tpu.distributed.moe.dispatch_combine): unused capacity slots
travel as zeros instead of being compacted away.  These helpers are the
explicit shard_map-path primitives; the MoELayer nn API instead lets the
XLA partitioner insert the identical collective from a sharding
constraint.

Both functions must run INSIDE a shard_map body over the `ep` mesh axis.
"""
from __future__ import annotations

import jax

__all__ = ["global_scatter", "global_gather"]


def global_scatter(x, axis="ep"):
    """[E, C, d] locally-routed tokens -> [E/ep, ep*C, d] per-expert rows.

    Each device enters holding the tokens IT routed for all E global
    experts; it leaves holding every device's tokens for its E/ep local
    experts — the reference's global_scatter (send side of the MoE
    all-to-all), as one XLA AllToAll over ICI.
    """
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=1,
                              tiled=True)


def global_gather(x, axis="ep"):
    """Inverse of global_scatter: [E/ep, ep*C, d] expert outputs back to
    [E, C, d] on the device that originally routed each token."""
    return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                              tiled=True)
