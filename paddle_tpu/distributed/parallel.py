"""DataParallel. Reference: python/paddle/fluid/dygraph/parallel.py.

TPU-native: no gradient-fusion buckets or NCCL allreduce hooks — the model's
parameters are replicated over the `dp` mesh axis and the batch is sharded;
when the train step runs under to_static over the mesh, XLA inserts a single
fused AllReduce for the gradients (ICI-optimal). In eager multi-host mode,
grad sync happens explicitly in `apply_collective_grads`.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.engine import no_grad
from paddle_tpu.nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.add_sublayer("_layers_holder", layers)

    @property
    def _inner(self):
        return self._sub_layers["_layers_holder"]

    def forward(self, *inputs, **kwargs):
        return self._inner(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._inner.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._inner.set_state_dict(sd, *a, **kw)

    @no_grad()
    def apply_collective_grads(self):
        """Average gradients across data-parallel workers (eager path)."""
        from paddle_tpu.distributed.collective import all_reduce, get_world_size
        ws = get_world_size(self.group)
        if ws <= 1:
            return
        for p in self._inner.parameters():
            if p.grad is not None:
                all_reduce(p.grad, group=self.group)
                p.grad._set_value(p.grad._value / ws)

    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
