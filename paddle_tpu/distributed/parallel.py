"""DataParallel. Reference: python/paddle/fluid/dygraph/parallel.py.

TPU-native: no gradient-fusion buckets or NCCL allreduce hooks — the model's
parameters are replicated over the `dp` mesh axis and the batch is sharded;
when the train step runs under to_static over the mesh, XLA inserts a single
fused AllReduce for the gradients (ICI-optimal). In eager multi-host mode,
grad sync happens explicitly in `apply_collective_grads`.
"""
from __future__ import annotations

import jax

from paddle_tpu.core.engine import no_grad
from paddle_tpu.nn.layer.layers import Layer


def _int8_grad_sync(grad, group, ws, bits=8, key=None):
    """Quantized mean-allreduce of one grad tensor over the collective
    layer: shared MAX-allreduced scale, int32 SUM, dequant/ws — the
    eager-path form of quantized_collective.quantized_all_reduce_mean.
    `bits`/`key` thread a CollectivePolicy's code width and stochastic-
    rounding key through (defaults reproduce comm_dtype="int8")."""
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.collective import ReduceOp, all_reduce

    # shares the quantization contract (qmax, clip, scale guard) with
    # the shard_map-level collective — one definition, two transports
    from paddle_tpu.distributed.quantized_collective import _quantize

    qmax = float(2 ** (int(bits) - 1) - 1)
    g = grad._value.astype(jnp.float32)
    smax = Tensor(jnp.max(jnp.abs(g)))
    all_reduce(smax, op=ReduceOp.MAX, group=group)
    scale = smax._value
    q = Tensor(_quantize(g, scale, qmax, key))
    all_reduce(q, group=group)
    grad._set_value(
        (q._value.astype(jnp.float32) * (jnp.maximum(scale, 1e-30)
                                         / qmax) / ws)
        .astype(grad._value.dtype))
    return grad


class DataParallel(Layer):
    """comm_dtype="int8" switches the eager gradient sync to the
    quantized all-reduce (distributed/quantized_collective.py — one
    global scale, exact integer summation, int32 wire payload; ~4x
    effective ICI bandwidth with narrow-wire collective support)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, comm_dtype=None):
        super().__init__()
        self._layers = layers
        self.group = group
        if comm_dtype not in (None, "int8"):
            raise ValueError(
                f"comm_dtype must be None or 'int8', got {comm_dtype!r}")
        self._comm_dtype = comm_dtype
        self.add_sublayer("_layers_holder", layers)

    @property
    def _inner(self):
        return self._sub_layers["_layers_holder"]

    def forward(self, *inputs, **kwargs):
        return self._inner(*inputs, **kwargs)

    def state_dict(self, *a, **kw):
        return self._inner.state_dict(*a, **kw)

    def set_state_dict(self, sd, *a, **kw):
        return self._inner.set_state_dict(sd, *a, **kw)

    @no_grad()
    def apply_collective_grads(self):
        """Average gradients across data-parallel workers (eager path)."""
        from paddle_tpu.distributed.collective import (ReduceOp,
                                                       all_reduce,
                                                       get_world_size)
        ws = get_world_size(self.group)
        if ws <= 1:
            return
        # the trace-scoped quantization policy selects the int8 sync
        # per tensor, honoring its whole contract — min_elems keeps
        # tiny (latency-bound) grads full-precision, bits/key thread
        # through — while comm_dtype="int8" keeps its historical
        # quantize-everything-at-8-bits behavior
        # (quantization.quantized_collectives(); docs/quantization.md)
        import jax.numpy as jnp

        from paddle_tpu.quantization.policy import \
            current_collective_policy
        pol = current_collective_policy()
        for i, p in enumerate(self._inner.parameters()):
            if p.grad is None:
                continue
            g = p.grad._value
            if self._comm_dtype == "int8":
                _int8_grad_sync(p.grad, self.group, ws)
            elif pol is not None and \
                    jnp.issubdtype(g.dtype, jnp.floating) and \
                    g.size >= pol.min_elems:
                import jax
                key = (None if pol.key is None
                       else jax.random.fold_in(pol.key, i))
                _int8_grad_sync(p.grad, self.group, ws,
                                bits=pol.bits, key=key)
            else:
                all_reduce(p.grad, group=self.group)
                p.grad._set_value(p.grad._value / ws)

    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()
