"""SPMD pipeline parallelism over the `pp` mesh axis.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (PipelineParallel: 1F1B/FThenB microbatch schedules
driven by NCCL p2p send/recv between stage ranks) and
pp_layers.py PipelineLayer (stage segmentation).

TPU-native design: no p2p runtime and no per-rank programs — ONE SPMD
program where each device along the `pp` axis owns one stage's weights
(stacked pytree sharded on the leading stage dim) and activations hop
stage→stage+1 with `lax.ppermute` over ICI. The microbatch loop is a
`lax.scan` of M + n - 1 ticks: stage 0 injects microbatch t, stage n-1
drains tick t's result into the output buffer; every device runs the same
`stage_fn` each tick so the MXU stays busy once the bubble fills. Reverse-
mode AD through scan+ppermute yields the backward pipeline automatically
(FThenB/GPipe schedule); `jax.checkpoint` on the tick keeps residuals to
one activation per tick.

Constraint (idiomatic for SPMD pipelining): all stages share one param
pytree structure and one inter-stage activation shape — put the embedding
and the head outside the pipelined trunk.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod


def stack_stage_params(stage_params):
    """Stack a list of per-stage param pytrees (identical structure/shapes)
    along a new leading `stage` dim — the dim sharded over `pp`."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *stage_params)


def unstack_stage_params(stacked, num_stages):
    """Inverse of stack_stage_params (host-side convenience)."""
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(num_stages)]


def pipeline_spmd_fn(stage_fn, axis_name="pp", axis_size=None,
                     checkpoint=True):
    """Build the per-device pipeline body (call INSIDE shard_map).

    stage_fn(params, x_mb) -> y_mb with x_mb/y_mb the same shape/dtype.
    Returned body(params_local, x) takes the local stage's params (leading
    stage dim of size 1) and the full microbatch stream x: [M, mb, ...],
    and returns [M, mb, ...] on every device (psum-broadcast from the last
    stage).
    """
    def body(params_local, x):
        n = mesh_mod.resolve_axis_size(axis_name, axis_size)
        stage = lax.axis_index(axis_name)
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        M = x.shape[0]
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(prev_y, t):
            # carry stays O(mb): per-tick results leave as stacked scan
            # outputs, not via an [M, ...] buffer in the carry (which would
            # make scan AD residuals O(M^2*mb))
            inbound = lax.ppermute(prev_y, axis_name, perm)
            inp = jnp.where(stage == 0, x[jnp.clip(t, 0, M - 1)], inbound)
            y = stage_fn(params, inp)
            return y, y

        y0 = jnp.zeros(x.shape[1:], x.dtype)
        fn = jax.checkpoint(tick) if checkpoint else tick
        _, ys = lax.scan(fn, y0, jnp.arange(M + n - 1))
        # ticks n-1 .. M+n-2 drain microbatches 0..M-1 from the last stage;
        # zero elsewhere + psum broadcasts them to every pp rank
        outputs = jnp.where(stage == n - 1, ys[n - 1:], 0.0)
        return lax.psum(outputs, axis_name)

    return body


def pipeline_forward(stage_fn, stacked_params, x, axis_name="pp", mesh=None,
                     checkpoint=True):
    """Whole-array pipeline apply; owns the shard_map.

    stacked_params: pytree with leading stage dim n (stack_stage_params).
    x: [num_microbatches, microbatch, ...] inter-stage activations.
    Returns [num_microbatches, microbatch, ...], replicated over `pp`.
    """
    mesh = mesh or mesh_mod.ensure_mesh()
    n = mesh.shape[axis_name]
    body = pipeline_spmd_fn(stage_fn, axis_name=axis_name, axis_size=n,
                            checkpoint=checkpoint)
    param_specs = jax.tree_util.tree_map(
        lambda p: P(*([axis_name] + [None] * (p.ndim - 1))), stacked_params)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(*([None] * x.ndim))),
        out_specs=P(*([None] * x.ndim)),
        check_vma=False)(stacked_params, x)


def schedule_1f1b(num_microbatches, num_stages):
    """Pure-python rendering of the SPMD 1F1B timetable (for tests/docs).

    Returns {stage: [(tick, op, microbatch), ...]} with op in {"F","B"}.
    Forward of microbatch m runs on stage s at tick m + s; backward at tick
    2*(num_stages-1) + m - s. In steady state every stage alternates one
    forward and one backward per tick — the 1F1B invariant; at most
    2*(num_stages-1)+1 microbatches are ever in flight on stage 0
    (vs num_microbatches for GPipe/FThenB).
    """
    M, n = num_microbatches, num_stages
    out = {s: [] for s in range(n)}
    for t in range(M + 2 * (n - 1)):
        for s in range(n):
            f = t - s
            if 0 <= f < M:
                out[s].append((t, "F", f))
            b = t - 2 * (n - 1) + s
            if 0 <= b < M:
                out[s].append((t, "B", b))
    return out


def pipeline_1f1b_fn(stage_fn, loss_fn, axis_name="pp", axis_size=None):
    """Explicit 1F1B forward+backward pipeline schedule (call INSIDE
    shard_map). Reference: fleet/meta_parallel/pipeline_parallel.py:117
    `forward_backward_pipeline` ("use the 1f1b scheduling strategy").

    TPU-native: the reference drives 1F1B with per-rank NCCL p2p send/recv;
    here ONE lax.scan of M + 2*(pp-1) ticks runs on every pp rank, each tick
    doing one forward (activation hops forward via ppermute) AND one
    backward (cotangent hops backward via a reverse ppermute). Backward is
    explicit (jax.vjp per stage with recompute from a saved stage input),
    NOT outer AD — that is what lets fwd and bwd interleave. Stage inputs
    live in a ring buffer of min(M, 2*pp-1) slots, so activation memory is
    O(pp), independent of the microbatch count (GPipe stores O(M + pp)
    per-tick residuals).

    stage_fn(stage_params, x) -> y      same x/y shape across stages
    loss_fn(loss_params, y, aux) -> scalar loss of ONE microbatch
        (runs on the last stage: e.g. final norm + LM head + CE)

    Returns body(params_local, loss_params, x_mb, aux_mb) ->
        (loss_sum, stage_grads_local, loss_param_grads, dx_mb)
    where stage_grads_local has the same leading stage dim of 1 as
    params_local, loss_param_grads/dx_mb are psum-replicated over pp, and
    loss_sum is the SUM over microbatches (caller normalizes).
    """
    def body(params_local, loss_params, x, aux):
        n = mesh_mod.resolve_axis_size(axis_name, axis_size)
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        loss_sum, gparams, gloss, dx_mb = pipeline_1f1b_body(
            stage_fn, loss_fn, params, loss_params, x, aux,
            axis_name=axis_name, axis_size=n)
        stage_grads = jax.tree_util.tree_map(lambda a: a[None], gparams)
        return loss_sum, stage_grads, gloss, dx_mb

    return body


def pipeline_1f1b_body(stage_fn, loss_fn, params, loss_params, x, aux,
                       axis_name="pp", axis_size=None):
    """Core 1F1B schedule on per-device stage params (no leading-dim
    convention) — shared by pipeline_1f1b_fn and the hybrid GPT flagship
    (models/gpt_hybrid.py), whose stage params carry a local layer stack.

    Returns (loss_sum, stage_param_grads_local, loss_param_grads, dx_mb);
    loss_param_grads and dx_mb are psum-replicated over `axis_name`,
    stage_param_grads stay local to this stage.
    """
    def body(params, loss_params, x, aux):
        n = mesh_mod.resolve_axis_size(axis_name, axis_size)
        stage = lax.axis_index(axis_name)
        is_last = stage == n - 1
        M = x.shape[0]
        R = min(M, 2 * n - 1)
        T = M + 2 * (n - 1)
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]
        bwd_perm = [(i, (i - 1) % n) for i in range(n)]
        zero_y = jnp.zeros(x.shape[1:], x.dtype)

        def tick(c, t):
            # ---------- forward half ----------
            f_mb = t - stage
            f_valid = (f_mb >= 0) & (f_mb < M)
            f_idx = jnp.clip(f_mb, 0, M - 1)
            inbound = lax.ppermute(c["fwd_out"], axis_name, fwd_perm)
            inp = jnp.where(stage == 0, x[f_idx], inbound)
            y = stage_fn(params, inp)
            slot = f_idx % R
            saved = c["saved"].at[slot].set(
                jnp.where(f_valid, inp, c["saved"][slot]))
            # last stage closes this microbatch NOW: loss + dy (1F1B's
            # defining move — backward starts the tick forward finishes)
            loss_m, (d_lp, dy) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(loss_params, y, aux[f_idx])
            # ---------- backward half ----------
            b_mb = t - 2 * (n - 1) + stage
            b_valid = (b_mb >= 0) & (b_mb < M)
            b_idx = jnp.clip(b_mb, 0, M - 1)
            g_in = lax.ppermute(c["bwd_out"], axis_name, bwd_perm)
            g = jnp.where(is_last, dy, g_in)
            g = jnp.where(b_valid, g, 0.0)       # zero cotangent => zero
            x_saved = saved[b_idx % R]           # grads (vjp is linear)
            _, vjp = jax.vjp(stage_fn, params, x_saved)
            d_params, d_x = vjp(g)
            keep_loss = f_valid & is_last
            carry = {
                "fwd_out": y,
                "bwd_out": d_x,
                "saved": saved,
                "gparams": jax.tree_util.tree_map(
                    lambda a, b: a + b, c["gparams"], d_params),
                "gloss": jax.tree_util.tree_map(
                    lambda a, b: a + jnp.where(keep_loss, b, 0.0),
                    c["gloss"], d_lp),
                "loss": c["loss"] + jnp.where(keep_loss, loss_m, 0.0),
            }
            return carry, d_x

        init = {
            "fwd_out": zero_y,
            "bwd_out": zero_y,
            "saved": jnp.zeros((R,) + x.shape[1:], x.dtype),
            "gparams": jax.tree_util.tree_map(jnp.zeros_like, params),
            "gloss": jax.tree_util.tree_map(jnp.zeros_like, loss_params),
            "loss": jnp.asarray(0.0, jnp.float32),
        }
        c, dxs = lax.scan(tick, init, jnp.arange(T))
        # stage 0's backward of mb m ran at tick 2*(n-1) + m
        dx_mb = lax.psum(
            jnp.where(stage == 0, dxs[2 * (n - 1):], 0.0), axis_name)
        loss_sum = lax.psum(c["loss"], axis_name)     # nonzero on last only
        gloss = jax.tree_util.tree_map(
            lambda a: lax.psum(a, axis_name), c["gloss"])
        return loss_sum, c["gparams"], gloss, dx_mb

    return body(params, loss_params, x, aux)


def interleave_layer_permutation(num_layers, pp, v):
    """Row permutation placing layers for the interleaved schedule.

    With V virtual chunks per device, device d's chunk c is LOGICAL stage
    l = c*pp + d (Megatron's interleaved assignment, reference
    pipeline_parallel.py:461 PipelineParallelWithInterleave). The stacked
    layer array is sharded contiguously over pp, so stored row
    d*(L/pp) + c*(L/(pp*v)) + j must hold logical layer
    (c*pp + d)*(L/(pp*v)) + j. Returns `perm` with
    stored[i] = logical[perm[i]].
    """
    if num_layers % (pp * v):
        raise ValueError("num_layers must divide by pp*v")
    lc = num_layers // (pp * v)       # layers per chunk
    l_loc = num_layers // pp          # layers per device
    perm = np.empty(num_layers, np.int64)
    for d in range(pp):
        for c in range(v):
            for j in range(lc):
                perm[d * l_loc + c * lc + j] = (c * pp + d) * lc + j
    return perm


def pipeline_interleaved_forward_fn(chunk_fn, axis_name="pp",
                                    axis_size=None, num_chunks=1):
    """Interleaved (virtual-stage) pipeline forward — call INSIDE
    shard_map. Reference: fleet/meta_parallel/pipeline_parallel.py:461
    (PipelineParallelWithInterleave).

    TPU-native rendering: ONE folded ring. Each device holds `num_chunks`
    (V) model chunks; a microbatch makes pp*V hops around the pp-device
    ring, crossing to its next chunk each time it wraps past the last
    device (the seam). Each tick every device runs ONE chunk — 1/V of a
    non-interleaved stage — so the fill/drain bubble costs (pp-1) CHUNK
    units instead of (pp-1) full-stage units: the bubble shrinks by V,
    which is the whole point of the interleaved schedule. Injection of
    new microbatches at device 0 is phase-gated (groups of pp, Megatron's
    grouping) so it never collides with a seam crossing. Backward is the
    AD transpose of the scan — it replays the same interleaved schedule
    in reverse (the explicit-1F1B composition stays with the
    non-interleaved body, pipeline_1f1b_body).

    chunk_fn(chunk_params, x) -> y; the body below receives
    params_chunks whose leaves carry a leading [V, ...] chunk dim (see
    interleave_layer_permutation for the storage layout).

    Returned body(params_chunks, x) maps [M, mb, ...] -> [M, mb, ...]
    (replicated over pp). M must divide by pp (pad the microbatch count).
    """
    v = num_chunks

    def body(params_chunks, x):
        pp = mesh_mod.resolve_axis_size(axis_name, axis_size)
        d = lax.axis_index(axis_name)
        M = x.shape[0]
        if M % pp:
            raise ValueError(f"microbatches {M} must divide by pp {pp}")
        period = pp * v
        S = M * v                      # total stream ticks per device
        T = S + pp - 1                 # + ring fill
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        zero_y = jnp.zeros(x.shape[1:], x.dtype)

        def tick(y_prev, t):
            inbound = lax.ppermute(y_prev, axis_name, perm)
            s = t - d                  # this device's stream coordinate
            c = (s % period) // pp     # chunk being run this tick
            g = s // period            # microbatch group
            mb = g * pp + (s % pp)
            inject = jnp.logical_and(d == 0, c == 0)
            inp = jnp.where(inject, x[jnp.clip(mb, 0, M - 1)], inbound)
            params_c = jax.tree_util.tree_map(
                lambda p: lax.dynamic_index_in_dim(
                    p, jnp.clip(c, 0, v - 1), 0, keepdims=False),
                params_chunks)
            y = chunk_fn(params_c, inp)
            # final logical stage (last device, last chunk) emits
            emit = jnp.logical_and(d == pp - 1, c == v - 1)
            valid = jnp.logical_and(s >= 0, s < S)
            out = jnp.where(jnp.logical_and(emit, valid), y, 0.0)
            return y, out

        _, outs = lax.scan(jax.checkpoint(tick), zero_y, jnp.arange(T))
        # mb m finishes on device pp-1 at tick
        #   t(m) = (m//pp)*period + (v-1)*pp + (m%pp) + (pp-1)
        m_idx = jnp.arange(M)
        t_out = (m_idx // pp) * period + (v - 1) * pp + (m_idx % pp) \
            + (pp - 1)
        return lax.psum(outs[t_out], axis_name)

    return body


def pipeline_1f1b_interleaved_body(chunk_fn, loss_fn, params_chunks,
                                   loss_params, x, aux, axis_name="pp",
                                   axis_size=None, num_chunks=1):
    """Explicit interleaved 1F1B: virtual stages composed WITH the 1F1B
    schedule (call INSIDE shard_map). Reference:
    fleet/meta_parallel/pipeline_parallel.py:461
    (PipelineParallelWithInterleave) — whose interleave IS 1F1B with
    virtual stages: bubble/V AND the O(pp) activation-memory bound
    together (r3's forward-only folded ring kept only the bubble win).

    TPU-native timetable (one lax.scan, every pp rank): logical stage
    l = c*pp + d lives on device d = l % pp as chunk c = l // pp, so
    EVERY logical hop — seam crossings included — is the same +1 ring
    ppermute, and the cotangent hop is the same -1 ring. Device d's
    forward stream coordinate is s = t - d with
    chunk c = (s % (pp*V)) // pp, microbatch m = (s//(pp*V))*pp + s%pp
    (microbatches advance in groups of pp, Megatron's grouping); the
    backward of logical stage l for m runs at
    t_B = t_F(L-1, m) + (L-1-l), which works out to one forward chunk
    AND one backward chunk per device per tick — the 1F1B invariant at
    chunk granularity. Chunk inputs are saved in a ring of
    min(M*V, 2*pp*V - 1) slots and the per-stage backward is a
    recompute-vjp from the saved input, so activation memory is O(pp*V)
    chunk inputs, independent of the microbatch count.

    chunk_fn(chunk_params, x) -> y      (1/V of a stage's layers)
    loss_fn(loss_params, y, aux) -> scalar microbatch loss (last stage)
    params_chunks: pytree with leading [V, ...] chunk dim per leaf
    (storage layout per interleave_layer_permutation).

    Returns (loss_sum, chunk_param_grads [V-leading, local],
    loss_param_grads, dx_mb) — same contract as pipeline_1f1b_body.
    M must divide by pp.
    """
    v = num_chunks

    def body(params_chunks, loss_params, x, aux):
        pp = mesh_mod.resolve_axis_size(axis_name, axis_size)
        d = lax.axis_index(axis_name)
        L = pp * v
        M = x.shape[0]
        if M % pp:
            raise ValueError(f"microbatches {M} must divide by pp {pp}")
        S = M * v                            # forward stream length
        R = min(S, 2 * L - 1)                # saved-input ring slots
        T = S + 2 * (L - 1) - (v - 1) * pp   # == v*(M+pp) + pp - 2
        period = pp * v
        fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
        bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
        zero_y = jnp.zeros(x.shape[1:], x.dtype)
        last_dev = d == pp - 1

        def chunk_params_at(c):
            return jax.tree_util.tree_map(
                lambda p: lax.dynamic_index_in_dim(
                    p, jnp.clip(c, 0, v - 1), 0, keepdims=False),
                params_chunks)

        def tick(c_state, t):
            # ---------------- forward half ----------------
            s = t - d
            f_valid = (s >= 0) & (s < S)
            sc = jnp.clip(s, 0, S - 1)
            c_f = (sc % period) // pp
            mb_f = (sc // period) * pp + (sc % pp)
            inbound = lax.ppermute(c_state["fwd_out"], axis_name, fwd_perm)
            inject = (d == 0) & (c_f == 0)
            inp = jnp.where(inject, x[jnp.clip(mb_f, 0, M - 1)], inbound)
            y = chunk_fn(chunk_params_at(c_f), inp)
            slot_f = sc % R
            saved = c_state["saved"].at[slot_f].set(
                jnp.where(f_valid, inp, c_state["saved"][slot_f]))
            # last logical stage closes its microbatch NOW (loss + dy)
            loss_m, (d_lp, dy) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(loss_params, y,
                                         aux[jnp.clip(mb_f, 0, M - 1)])
            finishes = f_valid & last_dev & (c_f == v - 1)
            # ---------------- backward half ----------------
            # invert t_B = g*pp*v + k - c*pp + 2*pp*v - 2 - d  (w below)
            w = t - (2 * L - 2) + d
            k_b = jnp.mod(w, pp)
            c_b = jnp.mod(v - (jnp.mod(w, period) - k_b) // pp, v)
            g_b = (w - k_b + c_b * pp) // period
            mb_b = g_b * pp + k_b
            b_valid = (mb_b >= 0) & (mb_b < M)
            s_b = g_b * period + c_b * pp + k_b   # its fwd stream coord
            g_in = lax.ppermute(c_state["bwd_out"], axis_name, bwd_perm)
            is_last_logical = last_dev & (c_b == v - 1)
            g = jnp.where(is_last_logical, dy, g_in)
            g = jnp.where(b_valid, g, 0.0)       # zero cotangent => zero
            x_saved = saved[jnp.mod(jnp.clip(s_b, 0, S - 1), R)]
            _, vjp = jax.vjp(chunk_fn, chunk_params_at(c_b), x_saved)
            d_cparams, d_x = vjp(g)
            cb_idx = jnp.clip(c_b, 0, v - 1)
            new_state = {
                "fwd_out": y,
                "bwd_out": d_x,
                "saved": saved,
                "gparams": jax.tree_util.tree_map(
                    lambda G, dp: G.at[cb_idx].add(dp),
                    c_state["gparams"], d_cparams),
                "gloss": jax.tree_util.tree_map(
                    lambda a, b: a + jnp.where(finishes, b, 0.0),
                    c_state["gloss"], d_lp),
                "loss": c_state["loss"] + jnp.where(finishes, loss_m, 0.0),
            }
            emit_dx = (d == 0) & (c_b == 0) & b_valid
            return new_state, jnp.where(emit_dx, d_x, 0.0)

        init = {
            "fwd_out": zero_y,
            "bwd_out": zero_y,
            "saved": jnp.zeros((R,) + x.shape[1:], x.dtype),
            "gparams": jax.tree_util.tree_map(jnp.zeros_like,
                                              params_chunks),
            "gloss": jax.tree_util.tree_map(jnp.zeros_like, loss_params),
            "loss": jnp.asarray(0.0, jnp.float32),
        }
        c_state, dxs = lax.scan(tick, init, jnp.arange(T))
        # mb m's stage-0 backward tick: g*pp*v + k + 2*pp*v - 2 (d=0,c=0)
        m_idx = jnp.arange(M)
        t_dx = (m_idx // pp) * period + (m_idx % pp) + 2 * L - 2
        dx_mb = lax.psum(
            jnp.where(d == 0, dxs[t_dx], 0.0), axis_name)
        loss_sum = lax.psum(c_state["loss"], axis_name)
        gloss = jax.tree_util.tree_map(
            lambda a: lax.psum(a, axis_name), c_state["gloss"])
        return loss_sum, c_state["gparams"], gloss, dx_mb

    return body(params_chunks, loss_params, x, aux)


def microbatch(x, num_microbatches, batch_axis=0):
    """[B, ...] -> [M, B/M, ...] microbatch stream."""
    B = x.shape[batch_axis]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by {num_microbatches} "
                         "microbatches")
    x = jnp.moveaxis(x, batch_axis, 0)
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])


def unmicrobatch(x, batch_axis=0):
    """[M, mb, ...] -> [B, ...]."""
    y = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
    return jnp.moveaxis(y, 0, batch_axis)
