"""SPMD pipeline parallelism over the `pp` mesh axis.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (PipelineParallel: 1F1B/FThenB microbatch schedules
driven by NCCL p2p send/recv between stage ranks) and
pp_layers.py PipelineLayer (stage segmentation).

TPU-native design: no p2p runtime and no per-rank programs — ONE SPMD
program where each device along the `pp` axis owns one stage's weights
(stacked pytree sharded on the leading stage dim) and activations hop
stage→stage+1 with `lax.ppermute` over ICI. The microbatch loop is a
`lax.scan` of M + n - 1 ticks: stage 0 injects microbatch t, stage n-1
drains tick t's result into the output buffer; every device runs the same
`stage_fn` each tick so the MXU stays busy once the bubble fills. Reverse-
mode AD through scan+ppermute yields the backward pipeline automatically
(FThenB/GPipe schedule); `jax.checkpoint` on the tick keeps residuals to
one activation per tick.

Constraint (idiomatic for SPMD pipelining): all stages share one param
pytree structure and one inter-stage activation shape — put the embedding
and the head outside the pipelined trunk.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod


def stack_stage_params(stage_params):
    """Stack a list of per-stage param pytrees (identical structure/shapes)
    along a new leading `stage` dim — the dim sharded over `pp`."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *stage_params)


def unstack_stage_params(stacked, num_stages):
    """Inverse of stack_stage_params (host-side convenience)."""
    return [jax.tree_util.tree_map(lambda x: x[i], stacked)
            for i in range(num_stages)]


def pipeline_spmd_fn(stage_fn, axis_name="pp", axis_size=None,
                     checkpoint=True):
    """Build the per-device pipeline body (call INSIDE shard_map).

    stage_fn(params, x_mb) -> y_mb with x_mb/y_mb the same shape/dtype.
    Returned body(params_local, x) takes the local stage's params (leading
    stage dim of size 1) and the full microbatch stream x: [M, mb, ...],
    and returns [M, mb, ...] on every device (psum-broadcast from the last
    stage).
    """
    def body(params_local, x):
        n = mesh_mod.resolve_axis_size(axis_name, axis_size)
        stage = lax.axis_index(axis_name)
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        M = x.shape[0]
        perm = [(i, (i + 1) % n) for i in range(n)]

        def tick(prev_y, t):
            # carry stays O(mb): per-tick results leave as stacked scan
            # outputs, not via an [M, ...] buffer in the carry (which would
            # make scan AD residuals O(M^2*mb))
            inbound = lax.ppermute(prev_y, axis_name, perm)
            inp = jnp.where(stage == 0, x[jnp.clip(t, 0, M - 1)], inbound)
            y = stage_fn(params, inp)
            return y, y

        y0 = jnp.zeros(x.shape[1:], x.dtype)
        fn = jax.checkpoint(tick) if checkpoint else tick
        _, ys = lax.scan(fn, y0, jnp.arange(M + n - 1))
        # ticks n-1 .. M+n-2 drain microbatches 0..M-1 from the last stage;
        # zero elsewhere + psum broadcasts them to every pp rank
        outputs = jnp.where(stage == n - 1, ys[n - 1:], 0.0)
        return lax.psum(outputs, axis_name)

    return body


def pipeline_forward(stage_fn, stacked_params, x, axis_name="pp", mesh=None,
                     checkpoint=True):
    """Whole-array pipeline apply; owns the shard_map.

    stacked_params: pytree with leading stage dim n (stack_stage_params).
    x: [num_microbatches, microbatch, ...] inter-stage activations.
    Returns [num_microbatches, microbatch, ...], replicated over `pp`.
    """
    mesh = mesh or mesh_mod.ensure_mesh()
    n = mesh.shape[axis_name]
    body = pipeline_spmd_fn(stage_fn, axis_name=axis_name, axis_size=n,
                            checkpoint=checkpoint)
    param_specs = jax.tree_util.tree_map(
        lambda p: P(*([axis_name] + [None] * (p.ndim - 1))), stacked_params)
    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, P(*([None] * x.ndim))),
        out_specs=P(*([None] * x.ndim)),
        check_vma=False)(stacked_params, x)


def microbatch(x, num_microbatches, batch_axis=0):
    """[B, ...] -> [M, B/M, ...] microbatch stream."""
    B = x.shape[batch_axis]
    if B % num_microbatches:
        raise ValueError(f"batch {B} not divisible by {num_microbatches} "
                         "microbatches")
    x = jnp.moveaxis(x, batch_axis, 0)
    return x.reshape((num_microbatches, B // num_microbatches) + x.shape[1:])


def unmicrobatch(x, batch_axis=0):
    """[M, mb, ...] -> [B, ...]."""
    y = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
    return jnp.moveaxis(y, 0, batch_axis)
