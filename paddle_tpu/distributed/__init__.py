"""paddle_tpu.distributed. Reference: python/paddle/distributed/__init__.py.

TPU-native: a jax.sharding.Mesh + XLA collectives over ICI/DCN replace the
reference's NCCL/gloo process groups; multi-host init is jax.distributed.
"""
from paddle_tpu.distributed import fleet  # noqa: F401
from paddle_tpu.distributed.collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all_single,
    alltoall,
    barrier,
    broadcast,
    get_group,
    get_rank,
    get_world_size,
    new_group,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    wait,
)
from paddle_tpu.distributed.mesh import (  # noqa: F401
    collective_axis,
    get_mesh,
    init_mesh,
    named_sharding,
    set_mesh,
    shard_tensor,
)
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401
from paddle_tpu.distributed.recompute import recompute  # noqa: F401
from paddle_tpu.distributed import elastic, launch  # noqa: F401
from paddle_tpu.distributed.pipeline import (  # noqa: F401
    microbatch,
    pipeline_forward,
    stack_stage_params,
    unmicrobatch,
    unstack_stage_params,
)
from paddle_tpu.distributed.context_parallel import (  # noqa: F401
    all_to_all_attention,
    all_to_all_attention_bshd,
    gather_sequence,
    ring_attention,
    ring_attention_bshd,
    split_sequence,
)

_parallel_env_initialized = [False]


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Reference: python/paddle/distributed/parallel.py init_parallel_env
    (NCCL bootstrap). TPU-native: jax.distributed.initialize for multi-host
    (DCN coordination), then install the global mesh over all devices."""
    import jax
    if _parallel_env_initialized[0]:
        return
    if coordinator_address is not None or num_processes not in (None, 1):
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    from paddle_tpu.distributed.mesh import ensure_mesh
    ensure_mesh()
    _parallel_env_initialized[0] = True


def is_initialized():
    return _parallel_env_initialized[0]


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Single-controller JAX doesn't fork per device; run inline (the mesh
    gives SPMD parallelism). Multi-host launch is via paddle_tpu.distributed.launch."""
    return func(*args)
