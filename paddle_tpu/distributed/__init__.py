"""paddle_tpu.distributed. Reference: python/paddle/distributed/__init__.py.

TPU-native: a jax.sharding.Mesh + XLA collectives over ICI/DCN replace the
reference's NCCL/gloo process groups; multi-host init is jax.distributed.
"""
from paddle_tpu.distributed import fleet  # noqa: F401
from paddle_tpu.distributed.collective import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all_single,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    destroy_process_group,
    get_group,
    get_rank,
    get_world_size,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    irecv,
    isend,
    new_group,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    shift,
    wait,
)
from paddle_tpu.distributed import communication  # noqa: F401
from paddle_tpu.distributed import rpc  # noqa: F401
from paddle_tpu.distributed.entry_attr import (  # noqa: F401
    CountFilterEntry,
    ProbabilityEntry,
    ShowClickEntry,
)
from paddle_tpu.distributed.fleet.dataset import (  # noqa: F401
    InMemoryDataset,
    QueueDataset,
)
from paddle_tpu.distributed.mesh import (  # noqa: F401
    collective_axis,
    get_mesh,
    init_mesh,
    named_sharding,
    set_mesh,
    shard_tensor,
)
from paddle_tpu.distributed.parallel import DataParallel  # noqa: F401
from paddle_tpu.distributed.recompute import recompute  # noqa: F401
from paddle_tpu.distributed import elastic, launch  # noqa: F401
from paddle_tpu.distributed.elastic import Command  # noqa: F401
from paddle_tpu.distributed.pipeline import (  # noqa: F401
    microbatch,
    pipeline_forward,
    stack_stage_params,
    unmicrobatch,
    unstack_stage_params,
)
from paddle_tpu.distributed.context_parallel import (  # noqa: F401
    all_to_all_attention,
    all_to_all_attention_bshd,
    gather_sequence,
    ring_attention,
    ring_attention_bshd,
    split_sequence,
)

_parallel_env_initialized = [False]


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Reference: python/paddle/distributed/parallel.py init_parallel_env
    (NCCL bootstrap). TPU-native: jax.distributed.initialize for multi-host
    (DCN coordination), then install the global mesh over all devices."""
    import os

    import jax
    if _parallel_env_initialized[0]:
        return
    # no-arg call inside a launched worker: pick up the bootstrap env the
    # launcher (launch.py / utils.start_local_trainers) exported
    if coordinator_address is None:
        coordinator_address = os.environ.get("PADDLE_MASTER")
    if num_processes is None:
        v = os.environ.get("PADDLE_TRAINERS_NUM",
                           os.environ.get("PADDLE_NNODES"))
        num_processes = int(v) if v else None
    if process_id is None:
        v = os.environ.get("PADDLE_TRAINER_ID")
        process_id = int(v) if v else None
    if coordinator_address is not None or num_processes not in (None, 1):
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    from paddle_tpu.distributed.mesh import ensure_mesh
    ensure_mesh()
    _parallel_env_initialized[0] = True


def is_initialized():
    return _parallel_env_initialized[0]


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    local_rank = rank
    nranks = world_size


def spawn(func, args=(), nprocs=-1, **kwargs):
    """Single-controller JAX doesn't fork per device; run inline (the mesh
    gives SPMD parallelism). Multi-host launch is via paddle_tpu.distributed.launch."""
    return func(*args)


class ParallelMode:
    """Reference distributed/parallel.py ParallelMode constants."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel split op (reference distributed/collective.py
    split): run a linear/embedding whose weight is partitioned
    `num_partitions`-ways over the tensor-parallel mesh axis.

    The reference constructs per-rank weight shards and inserts
    c_concat/c_allreduce by hand; here the layer holds the full logical
    weight with a PartitionSpec over 'tp' and XLA partitions the matmul
    (fleet.meta_parallel Column/RowParallelLinear are the layer forms).
    """
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )
    mesh = get_mesh()
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    if num_partitions > 1 and tp not in (1, num_partitions):
        raise ValueError(
            f"num_partitions={num_partitions} does not match the mesh's "
            f"tp degree {tp}")
    if operation == "linear":
        # reference: axis=1 splits the OUT dim (column-parallel),
        # axis=0 splits the IN dim (row-parallel); bias_attr=False
        # disables the bias like the reference nn.Linear contract
        has_bias = bias_attr is not False
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=has_bias,
                                      input_is_parallel=False)
        else:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=has_bias,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError("operation must be 'linear' or 'embedding'")
