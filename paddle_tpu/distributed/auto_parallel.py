"""Auto-parallel API. Reference: python/paddle/distributed/auto_parallel/.

Thin TPU-native surface: ProcessMesh ~= jax.sharding.Mesh; shard_tensor
attaches PartitionSpecs (consumed by to_static's state lifting); shard_op is
a sharding-constraint wrapper.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from paddle_tpu.distributed import mesh as dmesh


class ProcessMesh:
    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
            self.shape = list(arr.shape)
        else:
            self.shape = list(shape or [])
        self.dim_names = list(dim_names or [f"d{i}" for i in range(len(self.shape))])

    def to_jax(self):
        devs = np.asarray(jax.devices()[:int(np.prod(self.shape))])
        return Mesh(devs.reshape(self.shape), tuple(self.dim_names))


def shard_tensor(x, process_mesh=None, shard_spec=None, mesh=None, placements=None):
    """paddle.distributed.shard_tensor parity: annotate + place."""
    spec = shard_spec if shard_spec is not None else placements
    if process_mesh is not None and dmesh.get_mesh() is None:
        dmesh.set_mesh(process_mesh.to_jax())
    if spec is None:
        return dmesh.shard_tensor(x)
    return dmesh.shard_tensor(x, *spec)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if out_shard_specs:
            from paddle_tpu.distributed.fleet.meta_parallel import _constrain
            out = _constrain(out, *out_shard_specs[0])
        return out
    return wrapped
