"""Auto-parallel API.

Reference: python/paddle/distributed/auto_parallel/ — ProcessMesh
(process_mesh.py:42), shard_tensor/shard_op (interface.py:28,:108),
reshard (reshard.py), Engine (engine.py).

TPU-native design: the reference's completion/planner/partitioner/cost
model — thousands of lines deciding where every op runs — IS the XLA
GSPMD partitioner here. Users annotate tensors (shard_tensor) or op
islands (shard_op) with placements; sharding propagation completes the
program and inserts the ICI collectives. ProcessMesh maps onto
jax.sharding.Mesh honoring explicit process_ids and sub-mesh slicing;
reshard is a device_put to the target NamedSharding (XLA emits the
collective); Engine is a compact prepare/fit loop whose train step is
to_static-compiled once over the installed mesh.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as dmesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "reshard",
           "Shard", "Replicate", "Engine"]


class Shard:
    """Placement for mesh dim i: shard tensor dim `dim` over it
    (paddle 2.x dtensor placements API)."""

    def __init__(self, dim):
        self.dim = int(dim)

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class ProcessMesh:
    """N-d logical mesh over (a subset of) the devices.

    mesh / (shape, process_ids): explicit device-id array — the ids
    select WHICH devices participate (reference semantics; the r2 shim
    ignored them). Supports sub-mesh slicing by dim name and equality.
    """

    def __init__(self, mesh=None, dim_names=None, shape=None,
                 process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        elif process_ids is not None:
            if shape is None:
                shape = [len(process_ids)]
            arr = np.asarray(process_ids).reshape(shape)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self._ids = arr
        self.shape = list(arr.shape)
        self.dim_names = list(
            dim_names or [f"d{i}" for i in range(arr.ndim)])
        if len(self.dim_names) != arr.ndim:
            raise ValueError("dim_names must match mesh rank")

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def ndim(self):
        return self._ids.ndim

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self.dim_names == other.dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self.dim_names)))

    def get_mesh_with_dim(self, dim_name, index=0):
        """Sub-mesh along `dim_name` at position `index` of the other
        dims (e.g. the tp ring this rank belongs to)."""
        if dim_name not in self.dim_names:
            raise KeyError(dim_name)
        ax = self.dim_names.index(dim_name)
        idx = [index] * self._ids.ndim
        idx[ax] = slice(None)
        return ProcessMesh(self._ids[tuple(idx)], dim_names=[dim_name])

    def to_jax(self):
        by_id = {d.id: d for d in jax.devices()}
        flat = [by_id[int(i)] for i in self._ids.reshape(-1)]
        devs = np.array(flat, dtype=object).reshape(self._ids.shape)
        return Mesh(devs, tuple(self.dim_names))


def _entries_from(placements_or_spec, tensor_ndim, mesh_dim_names):
    """Normalize a shard_spec list (mesh-axis names / None, one per
    TENSOR dim) or a placements list (Shard/Replicate, one per MESH dim)
    into the per-tensor-dim axis-name form dmesh.shard_tensor consumes."""
    entries = list(placements_or_spec)
    if not any(isinstance(e, (Shard, Replicate)) for e in entries):
        return entries
    spec = [None] * tensor_ndim
    for mesh_dim, e in enumerate(entries):
        if isinstance(e, Shard):
            if spec[e.dim] is not None:
                raise ValueError(
                    f"tensor dim {e.dim} sharded by two mesh dims")
            spec[e.dim] = mesh_dim_names[mesh_dim]
    return spec


def shard_tensor(x, process_mesh=None, shard_spec=None, mesh=None,
                 placements=None):
    """Annotate + place a tensor. Accepts both the classic
    (process_mesh, shard_spec) form and the dtensor (mesh, placements)
    form; installs the ProcessMesh globally if none is active."""
    pm = process_mesh if process_mesh is not None else mesh
    explicit = pm is not None
    if isinstance(pm, ProcessMesh):
        jmesh = pm.to_jax()
    elif isinstance(pm, Mesh):
        jmesh = pm
    else:
        jmesh = dmesh.get_mesh()
    if jmesh is not None and dmesh.get_mesh() is None:
        dmesh.set_mesh(jmesh)
    entries = placements if placements is not None else shard_spec
    if entries is None:
        return dmesh.shard_tensor(x)
    nd = len(x.shape)
    names = list(jmesh.axis_names) if jmesh is not None else []
    norm = _entries_from(entries, nd, names)
    if explicit and jmesh is not None and jmesh is not dmesh.get_mesh():
        # the user named a SPECIFIC mesh (possibly a device subset) that
        # differs from the installed global one: place directly on it —
        # routing through the global mesh would silently degrade any axis
        # it doesn't know to replicated
        spec = PartitionSpec(*norm)
        val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        out = jax.device_put(val, NamedSharding(jmesh, spec))
        t = x if isinstance(x, Tensor) else Tensor(out)
        t._value = out
        t.__dict__["dist_spec"] = spec
        return t
    return dmesh.shard_tensor(x, *norm)


def reshard(x, mesh=None, placements=None, process_mesh=None,
            shard_spec=None):
    """Move a (possibly already placed) tensor to a new placement
    (reference reshard.py): a device_put to the target NamedSharding —
    XLA emits the actual resharding collective."""
    pm = process_mesh if process_mesh is not None else mesh
    jmesh = pm.to_jax() if isinstance(pm, ProcessMesh) else \
        (pm or dmesh.get_mesh())
    if jmesh is None:
        raise ValueError("reshard requires a mesh")
    entries = placements if placements is not None else shard_spec
    nd = len(x.shape)
    spec = PartitionSpec(*_entries_from(entries, nd,
                                        list(jmesh.axis_names)))
    val = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    out = jax.device_put(val, NamedSharding(jmesh, spec))
    if isinstance(x, Tensor):
        x._value = out
        # refresh the annotation shard_tensor left, or to_static's state
        # lift would re-apply the PRE-reshard placement
        x.__dict__["dist_spec"] = spec
        return x
    return Tensor(out)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None,
             out_shard_specs=None):
    """Wrap an op with INPUT and output sharding constraints: the wrapped
    op becomes a GSPMD island whose boundary layouts are pinned while
    propagation fills in the interior (the r2 shim dropped
    in_shard_specs)."""
    from paddle_tpu.core.dispatch import apply

    def _constrain_one(t, spec, jmesh):
        if spec is None or not isinstance(t, Tensor):
            return t
        pspec = PartitionSpec(*_entries_from(spec, len(t.shape),
                                             list(jmesh.axis_names)))
        return apply(lambda v: jax.lax.with_sharding_constraint(
            v, NamedSharding(jmesh, pspec)), t)

    def wrapped(*args, **kwargs):
        jmesh = (process_mesh.to_jax()
                 if isinstance(process_mesh, ProcessMesh)
                 else dmesh.get_mesh())
        if jmesh is not None and in_shard_specs:
            specs = list(in_shard_specs) + [None] * len(args)
            args = tuple(_constrain_one(a, s, jmesh)
                         for a, s in zip(args, specs))
        out = op_fn(*args, **kwargs)
        if jmesh is None or not out_shard_specs:
            return out
        if isinstance(out, (tuple, list)):
            specs = list(out_shard_specs) + [None] * len(out)
            return type(out)(_constrain_one(o, s, jmesh)
                             for o, s in zip(out, specs))
        return _constrain_one(out, out_shard_specs[0], jmesh)

    return wrapped


class Engine:
    """Compact auto-parallel trainer (reference engine.py Engine):
    prepare() compiles one to_static train step over the installed mesh;
    placement comes from shard_tensor annotations + GSPMD propagation —
    no manual partitioner pass."""

    def __init__(self, model=None, loss=None, optimizer=None,
                 metrics=None, strategy=None):
        self._model = model
        self._loss = loss
        self._opt = optimizer
        self._step = None

    def prepare(self, mesh=None):
        if isinstance(mesh, ProcessMesh):
            dmesh.set_mesh(mesh.to_jax())
        elif mesh is not None:
            dmesh.set_mesh(mesh)

        import paddle_tpu as P

        model, loss_fn, opt = self._model, self._loss, self._opt

        @P.jit.to_static
        def step(x, y):
            opt.clear_grad()
            loss = loss_fn(model(x), y)
            loss.backward()
            opt.step()
            return loss

        self._step = step
        return self

    def fit(self, train_data, epochs=1, verbose=0):
        if self._step is None:
            self.prepare()
        history = []
        for _ in range(epochs):
            loss = None
            for batch in train_data:
                loss = self._step(batch[0], batch[1])
            history.append(float(loss.numpy()))
            if verbose:
                print(f"epoch loss: {history[-1]:.4f}")
        return history

    def evaluate(self, data):
        model, loss_fn = self._model, self._loss
        model.eval()
        tot, n = 0.0, 0
        for batch in data:
            tot += float(loss_fn(model(batch[0]), batch[1]).numpy())
            n += 1
        model.train()
        return tot / max(n, 1)
