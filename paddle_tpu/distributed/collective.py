"""Collective ops. Reference: python/paddle/distributed/collective.py.

The reference's c_allreduce/c_broadcast/... ops dispatch NCCL kernels; here
each collective is an XLA collective on a mesh axis:
  - inside a shard_map body (collective_axis set): lax.psum / all_gather /
    ppermute / all_to_all — compiled onto ICI.
  - eager multi-host (jax.distributed): multihost_utils fallbacks over DCN.
  - single process, no axis: identity (world of one).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as dmesh


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A mesh-axis-backed communication group."""

    def __init__(self, axis=None, ranks=None, id=0):
        self.axis = axis
        self.ranks = ranks or []
        self.id = id

    @property
    def nranks(self):
        if self.axis is not None:
            return dmesh.axis_size(self.axis)
        return get_world_size()

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return rank

    @property
    def rank(self):
        return get_rank()


_default_group = Group()


def new_group(ranks=None, backend=None, axis=None):
    return Group(axis=axis, ranks=ranks, id=1)


def get_group(gid=0):
    return _default_group


def _axis_of(group):
    if group is not None and getattr(group, "axis", None):
        return group.axis
    return dmesh.current_collective_axis()


def get_rank(group=None):
    axis = _axis_of(group)
    if axis is not None:
        # Inside a shard_map body this is a per-shard traced value — return
        # it as-is so rank-dependent code computes with the true rank on each
        # shard (an int() here would silently collapse every shard to rank 0).
        return jax.lax.axis_index(axis)
    # eager path: the FLEET rank — contiguous within the survivor set
    # after an elastic reconfigure (== jax.process_index() at launch)
    from paddle_tpu.resilience import fleet
    return fleet.world().rank


def get_world_size(group=None):
    axis = _axis_of(group)
    if axis is not None:
        return dmesh.axis_size(axis)
    from paddle_tpu.resilience import fleet
    return fleet.world().size


# monotone per-process round counter for coordination-service
# collectives; SPMD call order is identical on every process, so the
# same round id names the same collective fleet-wide.  Keys are
# namespaced by the fleet launch id + generation (fleet.coord_namespace)
# so an aborted run's debris can't collide with the next, and a clean
# exit / reconfigure reaps the whole namespace in one delete.
# _COORD_REAPED tracks the newest round PROVEN globally complete and
# already swept (see _coord_reap for the proof obligation).
_COORD_ROUND = [0]
_COORD_REAPED = [0]
_REAP_BATCH = 64     # max rounds swept per allgather (no delete storms)


def reset_coord_rounds():
    """Fresh round counters for a fresh key namespace — called by
    ``resilience.fleet.reconfigure`` after the generation bump (every
    survivor resets identically; the new namespace guarantees no
    collision with in-flight old-generation keys)."""
    _COORD_ROUND[0] = 0
    _COORD_REAPED[0] = 0


def _coord_client():
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "jax.distributed is not initialized — multi-process "
            "collectives need distributed.launch / "
            "jax.distributed.initialize first")
    return client


def _coord_get(client, key, missing_rank, rnd):
    """One peer contribution, timeout-bounded (fleet.kv_get_bytes):
    sliced blocking gets under the configured deadline, aborting early
    the moment the fleet watchdog holds a DEAD verdict for the awaited
    rank — raises ``CollectiveTimeout`` naming it, never hangs."""
    from paddle_tpu.resilience import fleet

    mon = fleet.get_monitor()
    abort_if = (None if mon is None
                else (lambda: mon.is_dead(missing_rank)))
    return fleet.kv_get_bytes(
        client, key, fleet.get_config().collective_timeout_s,
        site="fleet.kv_get", missing_rank=missing_rank,
        abort_if=abort_if, seed=rnd)


def _coord_allgather(value):
    """Eager cross-process allgather over the jax.distributed
    coordination service's key-value store (the same coordinator
    ``launch()`` / ``jax.distributed.initialize`` stood up).

    XLA:CPU cannot execute multi-process SPMD programs, so the
    ``multihost_utils`` path is TPU/GPU-only; this DCN fallback keeps
    the eager collective API working in multi-process CPU worlds
    (tests/test_distributed_multiprocess.py proves it end to end).
    Stacks every member's array along a new leading axis, in fleet
    member order — after an elastic reconfigure the world is the
    survivor set, not ``jax.process_count()``."""
    import pickle

    import numpy as np

    from paddle_tpu.resilience import fleet

    client = _coord_client()
    wv = fleet.world()
    _COORD_ROUND[0] += 1
    rnd = _COORD_ROUND[0]
    prefix = f"{fleet.coord_namespace()}/allgather/{rnd}"
    arr = np.asarray(value)
    fleet.kv_set_bytes(client, f"{prefix}/{wv.global_rank}",
                       pickle.dumps(arr))
    parts = []
    for r in wv.members:
        raw = _coord_get(client, f"{prefix}/{r}", r, rnd)
        parts.append(pickle.loads(raw))
    _coord_reap(client, wv.rank, rnd)
    return np.stack(parts)


def _coord_reap(client, rank, rnd):
    """Reap all rounds strictly BEFORE `rnd`, from inside an allgather
    whose every member key has just been received.  That receipt is
    the proof that makes the sweep safe: each member publishes its
    round-`rnd` key on ENTERING round `rnd`, so possession of all of
    them means every member has COMPLETED every earlier round —
    including broadcast rounds, whose non-src readers nothing else
    synchronizes (a calendar-style "two rounds behind" sweep could
    delete a bcast key a descheduled reader had not consumed, stranding
    it into a spurious CollectiveTimeout on a healthy fleet).  Round
    `rnd` itself is never touched: peers may still be mid-read on it.
    Both collective prefixes share the round counter, so both are
    swept, at most _REAP_BATCH rounds per call (a long broadcast-only
    streak must not turn the next allgather into a delete storm; the
    backlog amortizes over subsequent allgathers).  Known limitation:
    a workload that ONLY broadcasts accrues keys until its next
    allgather/barrier or the namespace reap at finalize/reconfigure —
    keys stay bounded by the namespace lifetime either way.  (Keys a
    mid-round abort leaves behind stay namespaced to this launch id +
    generation, and the whole namespace is reaped on clean exit and on
    reconfigure — this sweep only bounds STEADY-STATE growth.)"""
    if rank != 0:
        return
    from paddle_tpu.resilience import fleet
    ns = fleet.coord_namespace()
    sweep = range(_COORD_REAPED[0] + 1,
                  min(rnd, _COORD_REAPED[0] + 1 + _REAP_BATCH))
    for old in sweep:
        for prefix in (f"{ns}/allgather", f"{ns}/bcast"):
            try:
                client.key_value_delete(f"{prefix}/{old}")
            except Exception:
                pass
    if sweep:
        _COORD_REAPED[0] = sweep[-1]


def _coord_broadcast(value, src):
    """Eager cross-process broadcast over the coordination service:
    only `src` uploads its payload — one set + n gets, instead of the
    n uploads + n*n downloads a full allgather would move through the
    single gRPC coordinator for data only one rank actually has.
    `src` is a FLEET rank (index into the current member list)."""
    import pickle

    import numpy as np

    from paddle_tpu.resilience import fleet

    client = _coord_client()
    wv = fleet.world()
    src_global = wv.members[int(src)]
    _COORD_ROUND[0] += 1
    rnd = _COORD_ROUND[0]
    key = f"{fleet.coord_namespace()}/bcast/{rnd}/{src_global}"
    if wv.global_rank == src_global:
        fleet.kv_set_bytes(client, key, pickle.dumps(np.asarray(value)))
    out = pickle.loads(_coord_get(client, key, src_global, rnd))
    # no reap here: only an allgather proves every member has passed a
    # round (broadcast synchronizes nobody but the reader and src) —
    # _coord_reap fires from _coord_allgather, where the proof holds
    return out


def _process_allgather(value):
    """Backend-appropriate eager cross-process allgather."""
    if jax.default_backend() == "cpu":
        return _coord_allgather(value)
    from jax.experimental import multihost_utils
    return multihost_utils.process_allgather(value)


def _reduce_fn(op):
    def pprod(v, axis):
        return jnp.exp(jax.lax.psum(jnp.log(v), axis))

    def pavg(v, axis):
        return jax.lax.pmean(v, axis)

    table = {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
        ReduceOp.AVG: pavg,
        ReduceOp.PROD: pprod,
    }
    if op not in table:
        raise ValueError(f"unsupported ReduceOp {op!r}")
    return table[op]


def _quantized_policy_for(value, op):
    """The active CollectivePolicy when it covers this reduction:
    mesh-axis float SUM/AVG above the policy's size floor.  Everything
    else (integer payloads, MAX/MIN/PROD, tiny tensors, no policy)
    keeps the plain-XLA path — selection is explicit, never ambient."""
    if op not in (ReduceOp.SUM, ReduceOp.AVG):
        return None
    from paddle_tpu.quantization.policy import current_collective_policy
    pol = current_collective_policy()
    if pol is None:
        return None
    if not jnp.issubdtype(value.dtype, jnp.floating):
        return None
    if value.size < pol.min_elems:
        return None
    return pol


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None:
        pol = _quantized_policy_for(tensor._value, op)
        if pol is not None:
            # EQuARX-style int8-payload path (quantization/collectives:
            # block-scale -> all_to_all narrow -> f32 reduce -> requant
            # -> all_gather narrow), selected by the trace-scoped
            # quantization.quantized_collectives() policy
            from paddle_tpu.quantization.collectives import \
                quantized_all_reduce
            out = apply(
                lambda v: quantized_all_reduce(
                    v, axis, bits=pol.bits, block=pol.block, key=pol.key,
                    mean=(op == ReduceOp.AVG)).astype(v.dtype), tensor)
            tensor._inplace_assign(out)
            return tensor
        fn = _reduce_fn(op)
        out = apply(lambda v: fn(v, axis), tensor)
        tensor._inplace_assign(out)
        return tensor
    if jax.process_count() > 1:
        g = _process_allgather(tensor._value)
        red = {ReduceOp.SUM: jnp.sum, ReduceOp.MAX: jnp.max,
               ReduceOp.MIN: jnp.min, ReduceOp.PROD: jnp.prod,
               ReduceOp.AVG: jnp.mean}
        if op not in red:
            raise ValueError(f"unsupported ReduceOp {op!r}")
        tensor._set_value(red[op](g, axis=0))
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None:
        out = apply(lambda v: jax.lax.all_gather(v, axis), tensor)
        n = dmesh.axis_size(axis)
        for i in range(n):
            tensor_list.append(out[i])
        return tensor_list
    if jax.process_count() > 1:
        g = _process_allgather(tensor._value)
        for i in range(g.shape[0]):
            tensor_list.append(Tensor(g[i]))
        return tensor_list
    tensor_list.append(tensor.clone())
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return object_list


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis_of(group)
    from paddle_tpu.tensor.manipulation import concat
    stacked = concat(tensor_list, axis=0) if isinstance(tensor_list, (list, tuple)) \
        else tensor_list
    if axis is not None:
        out = apply(lambda v: jax.lax.psum_scatter(v, axis, tiled=True), stacked)
        tensor._inplace_assign(out)
        return tensor
    tensor._set_value(stacked._value)
    return tensor


def broadcast(tensor, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None:
        def fn(v):
            idx = jax.lax.axis_index(axis)
            return jax.lax.psum(jnp.where(idx == src, v, jnp.zeros_like(v)), axis)
        out = apply(fn, tensor)
        tensor._inplace_assign(out)
        return tensor
    if jax.process_count() > 1:
        if jax.default_backend() == "cpu":
            tensor._set_value(_coord_broadcast(tensor._value, src))
        else:
            from jax.experimental import multihost_utils
            tensor._set_value(
                multihost_utils.broadcast_one_to_all(tensor._value))
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if tensor_list is None:
        return tensor
    from paddle_tpu.tensor.manipulation import stack
    stacked = stack(tensor_list, axis=0)
    if axis is not None:
        def fn(v):
            idx = jax.lax.axis_index(axis)
            return jnp.take(v, idx, axis=0)
        out = apply(fn, stacked)
        tensor._inplace_assign(out)
        return tensor
    tensor._set_value(tensor_list[0]._value)
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    axis = _axis_of(group)
    from paddle_tpu.tensor.manipulation import stack
    stacked = stack(in_tensor_list, axis=0) if isinstance(in_tensor_list, (list, tuple)) \
        else in_tensor_list
    if axis is not None:
        out = apply(lambda v: jax.lax.all_to_all(v, axis, split_axis=0,
                                                 concat_axis=0, tiled=False), stacked)
        n = dmesh.axis_size(axis)
        if out_tensor_list is not None:
            for i in range(n):
                out_tensor_list.append(out[i])
            return out_tensor_list
        return out
    if out_tensor_list is not None:
        out_tensor_list.extend([t.clone() for t in in_tensor_list])
        return out_tensor_list
    return stacked


def all_to_all_single(out_tensor, in_tensor, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is not None:
        out = apply(lambda v: jax.lax.all_to_all(
            v, axis, split_axis=0, concat_axis=0, tiled=True), in_tensor)
        out_tensor._inplace_assign(out)
        return out_tensor
    out_tensor._set_value(in_tensor._value)
    return out_tensor


def send(tensor, dst=0, group=None, sync_op=True):
    axis = _axis_of(group)
    if axis is None:
        raise RuntimeError("send/recv require a mesh axis (pipeline context)")
    # point-to-point on TPU == ppermute ring step; paired with recv
    raise RuntimeError("use paddle_tpu.distributed.p2p.ppermute_send_recv "
                       "inside shard_map (XLA has no one-sided send)")


def recv(tensor, src=0, group=None, sync_op=True):
    return send(tensor, src, group, sync_op)


def ppermute(tensor, perm, axis=None, group=None):
    """TPU-native p2p: permute values along a mesh axis ring (ICI neighbor
    exchange). perm: list of (src, dst)."""
    ax = axis or _axis_of(group)
    return apply(lambda v: jax.lax.ppermute(v, ax, perm), tensor)


def barrier(group=None):
    if jax.process_count() > 1:
        if jax.default_backend() == "cpu":
            # coordination-service barrier: a tiny allgather round is
            # timeout-bounded and fleet-membership-aware, unlike
            # sync_global_devices (which needs an SPMD-capable backend
            # and the full launch-time process set)
            import numpy as np
            _coord_allgather(np.zeros((1,), np.int8))
            return
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def wait(tensor, group=None, use_calc_stream=True):
    from paddle_tpu.core.tensor import sync_array
    sync_array(tensor._value)
    return tensor


alltoall_single = all_to_all_single  # reference exports both spellings


class shift:
    """Static peer pattern for batch_isend_irecv: every rank r talks to
    (r + offset) % world_size.  XLA's collective-permute takes one STATIC
    global edge list, so per-rank dynamic peer ints (the reference's
    NCCL contract) cannot lower from inside an SPMD region — uniform
    shifts are the expressible (and, for pipelines/rings, the actually
    used) pattern."""

    def __init__(self, offset):
        self.offset = int(offset)


class P2POp:
    """One point-to-point op for batch_isend_irecv (reference
    distributed/communication/batch_isend_irecv.py).  op is
    paddle.distributed.isend or .irecv; peer a `shift(k)` pattern (see
    shift) on the bound mesh axis."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise ValueError("op must be distributed.isend or "
                             "distributed.irecv")
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def isend(tensor, dst=0, group=None):
    """XLA has no one-sided send; use inside batch_isend_irecv, where a
    matched send/recv set becomes ONE ppermute over the mesh axis."""
    raise RuntimeError(
        "isend/irecv cannot run standalone on XLA (no one-sided p2p). "
        "Wrap them in P2POp(...) and run batch_isend_irecv([...]) — the "
        "batch lowers to a single collective-permute over ICI.")


def irecv(tensor, src=0, group=None):
    isend(tensor, src, group)


def batch_isend_irecv(p2p_op_list):
    """Execute matched isend/irecv pairs as ONE XLA collective-permute
    (reference batch_isend_irecv issues grouped NCCL p2p).  Each isend's
    (my_rank -> peer) edge must have the matching irecv posted on the
    destination; here the full edge list is the ppermute perm and every
    irecv tensor is assigned its permuted value.  Must run inside a
    shard_map / collective-axis context so ranks are defined."""
    from paddle_tpu.distributed import mesh as dmesh

    axis = dmesh.current_collective_axis()
    if axis is None:
        g = p2p_op_list[0].group if p2p_op_list else None
        axis = _axis_of(g)
    if axis is None:
        raise RuntimeError("batch_isend_irecv needs a mesh axis: run "
                           "inside shard_map/collective_axis or pass a "
                           "group bound to an axis")
    sends = [p for p in p2p_op_list if p.op is isend]
    recvs = [p for p in p2p_op_list if p.op is irecv]
    if len(sends) != len(recvs):
        raise ValueError(
            f"batch_isend_irecv needs matched send/recv pairs, got "
            f"{len(sends)} isend vs {len(recvs)} irecv — on XLA every "
            f"permuted value must land in a posted recv buffer")
    n = dmesh.axis_size(axis)
    tasks = []
    for s, r in zip(sends, recvs):
        if not isinstance(s.peer, shift) or not isinstance(r.peer, shift):
            raise TypeError(
                "on XLA, P2POp peers must be distributed.shift(offset) "
                "patterns (a collective-permute needs one static global "
                "edge list; absolute per-rank peer ints cannot be read "
                "inside the SPMD region)")
        if (r.peer.offset + s.peer.offset) % n != 0:
            raise ValueError(
                f"mismatched pair: isend shift({s.peer.offset}) delivers "
                f"to rank+{s.peer.offset}, so the matching irecv must be "
                f"shift({-s.peer.offset}), got shift({r.peer.offset})")
        perm = [(rr, (rr + s.peer.offset) % n) for rr in range(n)]
        out = apply(lambda v, p=tuple(perm): jax.lax.ppermute(v, axis, p),
                    s.tensor)
        r.tensor._inplace_assign(out)
        tasks.append(out)
    return tasks


def destroy_process_group(group=None):
    """Drop the installed mesh/groups (reference destroys NCCL comms)."""
    from paddle_tpu.distributed import mesh as dmesh
    if group is None:
        dmesh.set_mesh(None)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-side gloo bootstrap: jax.distributed covers both CPU and TPU
    meshes here, so this is init_parallel_env."""
    from paddle_tpu import distributed as dist
    dist.init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    return None
