"""Sparse-table entry policies (reference:
python/paddle/distributed/entry_attr.py): admission/eviction config for
parameter-server embedding tables (consumed by distributed.ps
SparseTable configs)."""
from __future__ import annotations

__all__ = ["EntryAttr", "ProbabilityEntry", "CountFilterEntry",
           "ShowClickEntry"]


class EntryAttr:
    def __init__(self):
        self._name = None

    def _to_attr(self):
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    """Admit a new feature id with fixed probability."""

    def __init__(self, probability):
        super().__init__()
        if not 0 <= probability <= 1:
            raise ValueError("probability must be in [0, 1]")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return f"{self._name}:{self._probability}"


class CountFilterEntry(EntryAttr):
    """Admit a feature id once it has been seen count_filter times."""

    def __init__(self, count_filter):
        super().__init__()
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return f"{self._name}:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    """Weight feature ids by show/click statistics columns."""

    def __init__(self, show_name, click_name):
        super().__init__()
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name/click_name must be str")
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return f"{self._name}:{self._show_name}:{self._click_name}"
