"""Beyond-HBM sparse embedding: a host-RAM parameter-server table.

Reference parity: python/paddle/distributed/ps/the_one_ps.py +
paddle.static.nn.sparse_embedding — the reference stores trillion-param
embedding tables on parameter servers; workers PULL the rows a batch
touches and PUSH sparse gradients back, with the optimizer applied
server-side.

TPU-native design: the "server" is host DRAM (orders of magnitude larger
than HBM). The table lives in a numpy array that never touches the
device; each training step pulls only the [batch, fields, dim] rows it
needs through `jax.pure_callback` (so the lookup works inside jit /
to_static programs) and pushes gradients back through an ordered
`io_callback` in the custom VJP, where a host-side optimizer (SGD /
Adagrad, the standard PS choice) folds duplicate ids with scatter-add.
HBM holds only the minibatch slice — table capacity is bounded by host
RAM, not by aggregate HBM, exactly like the reference's PS mode.

Multi-host: shard rows by `row_shard` (this host owns global rows
[offset, offset + local_rows)); out-of-shard ids pull zeros and drop
pushes, so each host's table plus an all-reduce of the dense tower is
the full PS picture. Single-host (the common test config) owns all rows.
"""
from __future__ import annotations

import threading

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

__all__ = ["SparseTable", "ps_embedding", "PSEmbedding"]


class SparseTable:
    """Host-RAM embedding table with sparse pull/push.

    optimizer: "sgd" or "adagrad" (server-side rule, applied at push).
    """

    def __init__(self, num_rows, dim, init_std=0.01, optimizer="adagrad",
                 learning_rate=0.05, epsilon=1e-8, seed=0,
                 dtype=np.float32, row_shard=None):
        rng = np.random.default_rng(seed)
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        if row_shard is None:
            self.row_offset, self.local_rows = 0, self.num_rows
        else:
            self.row_offset, self.local_rows = map(int, row_shard)
        self._data = (rng.standard_normal(
            (self.local_rows, self.dim)) * init_std).astype(self.dtype)
        self._opt = optimizer
        self._lr = float(learning_rate)
        self._eps = float(epsilon)
        if optimizer == "adagrad":
            self._acc = np.zeros((self.local_rows, self.dim), np.float32)
        elif optimizer != "sgd":
            raise ValueError(f"unsupported PS optimizer: {optimizer!r}")
        self._lock = threading.Lock()
        self._prefetched = {}
        self.pull_count = 0
        self.push_count = 0
        self._anchor = None
        self._native = None  # lazily probed (see _use_native)

    @property
    def anchor(self):
        """Zero scalar with stop_gradient=False: the autograd hook that
        routes output cotangents into push() (see ps_embedding)."""
        if self._anchor is None:
            self._anchor = Tensor(jnp.zeros((), jnp.float32),
                                  stop_gradient=False)
        return self._anchor

    # ------------------------------------------------------------- host side
    def _local(self, ids):
        loc = ids.astype(np.int64).reshape(-1) - self.row_offset
        ok = (loc >= 0) & (loc < self.local_rows)
        return loc, ok

    def pull(self, ids):
        """ids: int array (any shape) of GLOBAL row ids ->
        [*ids.shape, dim] rows (zeros for out-of-shard ids)."""
        ids = np.asarray(ids)
        key = ids.tobytes()
        with self._lock:
            pre = self._prefetched.pop(key, None)
        if pre is not None:
            return pre
        return self._pull_impl(ids)

    def _pull_impl(self, ids):
        # the row gather shares self._lock with push(): a prefetch
        # thread reading while the training thread applies an optimizer
        # step must see either the pre- or post-step rows, never a torn
        # mix (pull_count rides along so concurrent pulls don't lose
        # increments)
        if self._use_native():
            from paddle_tpu import native
            with self._lock:
                self.pull_count += 1
                return native.pstable_pull(self._data, ids,
                                           self.row_offset)
        loc, ok = self._local(ids)
        with self._lock:
            self.pull_count += 1
            rows = self._data[np.clip(loc, 0, self.local_rows - 1)]
        rows[~ok] = 0
        return rows.reshape(ids.shape + (self.dim,))

    def _use_native(self):
        """Native C++ kernels when the toolchain is up AND the table
        layout matches (fp32 contiguous).  One gather is internally
        multithreaded in C++; distinct pull/push CALLS serialize on
        the table lock (pull-vs-push atomicity: a reader must never
        see a half-applied optimizer step)."""
        if self._native is None:
            from paddle_tpu import native
            self._native = bool(
                native.pstable_available()
                and self.dtype == np.float32
                and self._data.flags["C_CONTIGUOUS"])
        return self._native

    def prefetch(self, ids):
        """Start an async host-side gather for a future pull of exactly
        these ids (overlaps the table read with device compute)."""
        ids = np.asarray(ids)
        key = ids.tobytes()

        def work():
            rows = self._pull_impl(ids)
            with self._lock:
                self._prefetched[key] = rows

        t = threading.Thread(target=work, daemon=True)
        t.start()
        return t

    def push(self, ids, grads):
        """Apply the server-side optimizer to grads for `ids` (duplicates
        within the batch are summed, like the PS's sparse merge)."""
        ids = np.asarray(ids)
        loc, ok = self._local(ids)
        if not ok.any():
            return  # nothing lands in this shard: counters untouched
        self.push_count += 1
        if self._use_native():
            from paddle_tpu import native
            with self._lock:
                native.pstable_push(
                    self._data, getattr(self, "_acc", None), ids, grads,
                    self.row_offset, self._lr, self._eps, self._opt)
            return
        g = np.asarray(grads, np.float32).reshape(-1, self.dim)[ok]
        loc = loc[ok]
        uniq, inv = np.unique(loc, return_inverse=True)
        merged = np.zeros((uniq.size, self.dim), np.float32)
        np.add.at(merged, inv, g)
        with self._lock:
            if self._opt == "adagrad":
                self._acc[uniq] += merged * merged
                step = merged / np.sqrt(self._acc[uniq] + self._eps)
            else:
                step = merged
            self._data[uniq] -= (self._lr * step).astype(self.dtype)

    @property
    def memory_bytes(self):
        """Host-RAM footprint of this shard (table + optimizer state)."""
        total = self._data.nbytes
        if hasattr(self, "_acc"):
            total += self._acc.nbytes
        return total

    def rows(self, ids):
        """Debug/eval helper: current host values for global ids."""
        return self._pull_impl(np.asarray(ids))


def ps_embedding(ids, table):
    """Differentiable PS lookup: pulls table rows through a host callback
    (jit-safe) and pushes gradients back to the host optimizer in the
    custom VJP.

    The integer ids alone would never trigger a backward node (autograd
    records only for differentiable inputs), so the lookup threads the
    table's zero-valued float `anchor` through the op — it contributes
    nothing to the value but makes the output require grad, which is what
    routes the output cotangent into the push callback.
    """

    @jax.custom_vjp
    def lookup(ids_v, anchor):
        out_sds = jax.ShapeDtypeStruct(ids_v.shape + (table.dim,),
                                       table.dtype)
        rows = jax.pure_callback(table.pull, out_sds, ids_v,
                                 vmap_method="sequential")
        return rows + anchor.astype(rows.dtype)

    def fwd(ids_v, anchor):
        return lookup(ids_v, anchor), ids_v

    def bwd(ids_v, ct):
        from jax.experimental import io_callback
        io_callback(table.push, None, ids_v, ct, ordered=True)
        return (np.zeros(ids_v.shape, jax.dtypes.float0),
                jnp.sum(ct).astype(jnp.float32))

    lookup.defvjp(fwd, bwd)
    # the anchor persists across steps (cached on the table) while its
    # .grad is re-written by every backward; under to_static a DISCOVERY
    # trace can abort (state registered lazily -> retrace) after backward
    # already wrote a tracer into anchor.grad — accumulating onto that
    # leaked tracer in the next trace is an UnexpectedTracerError. The
    # grad's value is never consumed (push() happens in the vjp), so
    # clear it on every entry.
    anchor = table.anchor
    anchor.clear_grad()
    return apply(lookup, ids if isinstance(ids, Tensor)
                 else Tensor(jnp.asarray(ids)), anchor)


class PSEmbedding:
    """Layer-ish wrapper: embedding lookup against a host SparseTable.
    Unlike nn.Embedding the weight is NOT a device parameter — it stays
    in host RAM and updates at push time (server-side optimizer), so it
    deliberately does not appear in parameters()/state_dict."""

    def __init__(self, num_embeddings, embedding_dim, **table_kwargs):
        self.table = SparseTable(num_embeddings, embedding_dim,
                                 **table_kwargs)

    def __call__(self, ids):
        return ps_embedding(ids, self.table)


# table/coordinator vocabulary at the reference paddle.distributed.ps path
from paddle_tpu.distributed.ps_tables import (  # noqa: E402,F401
    BarrierTable,
    ClientSelector,
    ClientSelectorBase,
    Coordinator,
    DenseTable,
    FLClient,
    FLClientBase,
    GlobalStepTable,
    Table,
    TensorTable,
)
