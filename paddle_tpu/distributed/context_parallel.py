"""Sequence/context parallelism for long sequences.

Reference parity: the reference scales sequence length via its fleet
sequence-parallel utilities (reference:
python/paddle/distributed/fleet/layers/mpu/mp_layers.py +
mp_ops.py `split`/`_c_split`/`_c_concat` over NCCL groups) and, in
derived suites, ring-style P2P attention. TPU-native design: the sequence
axis of the activations is a mesh axis (`sp`); k/v blocks rotate around the
ring with `lax.ppermute` over ICI while each step's partial attention is
merged online-softmax style — no materialised [s, s] score matrix and no
full k/v gather. The all-to-all variant (DeepSpeed-Ulysses-style) trades two
`lax.all_to_all`s for head-sharded full-sequence attention.

Both paths are plain differentiable JAX: reverse-mode AD through
`lax.scan` + `ppermute` yields the reverse ring automatically, and
`jax.checkpoint` on the ring step keeps scan residuals O(local kv) instead
of O(full kv).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.distributed import mesh as mesh_mod

_NEG_INF = -1e30


_axis_size = mesh_mod.resolve_axis_size


# ---------------------------------------------------------------------------
# Ring attention (inside shard_map; seq axis sharded over `axis_name`)
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   axis_size=None):
    """Exact attention with the sequence dim sharded over `axis_name`.

    Call INSIDE a shard_map body. q/k/v: [batch, heads, s_local, head_dim]
    (each device owns a contiguous chunk of the sequence, chunk index ==
    axis index). Returns [batch, heads, s_local, head_dim].

    k/v rotate around the ring: at step t, device i holds the chunk that
    started on device (i - t) mod n, so after n steps every q block has seen
    every kv block. Partial results merge with running (max, sum) softmax
    stats in fp32. Causal masking is by chunk index — a fully-future chunk
    contributes exp(-inf)=0 rows; the diagonal chunk masks col<=row.
    """
    n = _axis_size(axis_name, axis_size)
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    scale = float(scale)
    if n == 1:
        return _sdpa_ref(q, k, v, causal=causal, scale=scale)

    my_idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    qf = q.astype(jnp.float32) * scale

    perm = [(j, (j + 1) % n) for j in range(n)]

    def accumulate(acc, m, l, kt, vt, t):
        kv_idx = (my_idx - t) % n

        def compute(acc, m, l):
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kt.astype(jnp.float32))
            if causal:
                row = lax.broadcasted_iota(jnp.int32, (sq, kt.shape[2]), 0)
                col = lax.broadcasted_iota(jnp.int32, (sq, kt.shape[2]), 1)
                visible = jnp.logical_or(
                    kv_idx < my_idx,
                    jnp.logical_and(kv_idx == my_idx, col <= row))
                s = jnp.where(visible, s, _NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * corr + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vt.astype(jnp.float32))
            return acc_new, m_new, l_new

        if not causal:
            return compute(acc, m, l)
        # fully-future chunk: skip the einsums entirely, not mask-to--inf
        return lax.cond(kv_idx > my_idx,
                        lambda acc, m, l: (acc, m, l), compute, acc, m, l)

    def step(carry, t):
        # permute at loop entry so only n-1 ring hops run (the t=0 local
        # block is folded in before the scan)
        acc, m, l, kt, vt = carry
        kt = lax.ppermute(kt, axis_name, perm)
        vt = lax.ppermute(vt, axis_name, perm)
        acc, m, l = accumulate(acc, m, l, kt, vt, t)
        return (acc, m, l, kt, vt), None

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    acc, m, l = accumulate(acc0, m0, l0, k, v, 0)
    carry, _ = lax.scan(jax.checkpoint(step),
                        (acc, m, l, k, v), jnp.arange(1, n))
    acc, m, l = carry[0], carry[1], carry[2]
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe).astype(q.dtype)


def _sdpa_ref(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk",
                   q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


# ---------------------------------------------------------------------------
# All-to-all (Ulysses-style) sequence parallel attention
# ---------------------------------------------------------------------------

def all_to_all_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                         axis_size=None, attn_fn=None):
    """Sequence-parallel attention via two all-to-alls (inside shard_map).

    q/k/v: [batch, heads, s_local, head_dim] with heads % axis_size == 0.
    First all-to-all regathers the full sequence while scattering heads
    (s_local→s_full, heads→heads/n); full-sequence attention runs locally on
    the owned heads (so `causal` is exact); the second all-to-all restores
    the [heads, s_local] layout. Two all-to-alls ride ICI vs. the ring's
    n-1 ppermutes — better for moderate n, and it reuses the single-device
    flash kernel unchanged.
    """
    n = _axis_size(axis_name, axis_size)
    if attn_fn is None:
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        attn_fn = functools.partial(_sdpa_ref, causal=causal,
                                    scale=float(scale))
    elif causal or scale is not None:
        raise ValueError("attn_fn owns masking and scaling — do not also "
                         "pass causal/scale")
    if n == 1:
        return attn_fn(q, k, v)
    for name, t in (("q", q), ("k", k), ("v", v)):
        if t.shape[1] % n:
            raise ValueError(f"{name} heads {t.shape[1]} not divisible by "
                             f"axis {n} (GQA/MQA needs kv heads % {n} == 0)")

    def seq_gather(x):   # [b, h, s_loc, d] -> [b, h/n, s_full, d]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def seq_scatter(x):  # [b, h/n, s_full, d] -> [b, h, s_loc, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    o = attn_fn(seq_gather(q), seq_gather(k), seq_gather(v))
    return seq_scatter(o)


# ---------------------------------------------------------------------------
# Whole-array wrappers (shard_map over the installed mesh) — eager/test use
# ---------------------------------------------------------------------------

def wrap_bshd(fn, q, k, v, axis_name, mesh):
    mesh = mesh or mesh_mod.ensure_mesh()
    spec = P(None, axis_name, None, None)   # [b, s, h, d], seq sharded

    def body(qb, kb, vb):
        # transpose to [b, h, s_loc, d] for the kernels
        o = fn(jnp.transpose(qb, (0, 2, 1, 3)),
               jnp.transpose(kb, (0, 2, 1, 3)),
               jnp.transpose(vb, (0, 2, 1, 3)))
        return jnp.transpose(o, (0, 2, 1, 3))

    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def ring_attention_bshd(q, k, v, causal=False, scale=None, axis_name="sp",
                        mesh=None):
    """Ring attention over whole [batch, seq, heads, head_dim] arrays; this
    wrapper owns the shard_map (seq sharded over `axis_name`)."""
    mesh = mesh or mesh_mod.ensure_mesh()
    n = mesh.shape[axis_name]
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal,
                           scale=scale, axis_size=n)
    return wrap_bshd(fn, q, k, v, axis_name, mesh)


def all_to_all_attention_bshd(q, k, v, causal=False, scale=None,
                              axis_name="sp", mesh=None):
    """Ulysses attention over whole [batch, seq, heads, head_dim] arrays."""
    mesh = mesh or mesh_mod.ensure_mesh()
    n = mesh.shape[axis_name]
    fn = functools.partial(all_to_all_attention, axis_name=axis_name,
                           causal=causal, scale=scale, axis_size=n)
    return wrap_bshd(fn, q, k, v, axis_name, mesh)


# ---------------------------------------------------------------------------
# Sequence scatter/gather helpers (reference mp_ops.split/_c_concat analogue)
# ---------------------------------------------------------------------------

def split_sequence(x, axis_name="sp", seq_axis=1):
    """Shard `x` along its sequence dim over the mesh axis (device_put with a
    NamedSharding — the TPU analogue of mp_ops.split on the activations)."""
    mesh = mesh_mod.ensure_mesh()
    spec = [None] * x.ndim
    spec[seq_axis] = axis_name
    return jax.device_put(x, jax.sharding.NamedSharding(mesh, P(*spec)))


def gather_sequence(x, axis_name="sp", seq_axis=1):
    """Replicate a sequence-sharded array (analogue of mp_ops._c_concat)."""
    mesh = mesh_mod.ensure_mesh()
    return jax.device_put(
        x, jax.sharding.NamedSharding(mesh, P(*([None] * x.ndim))))
