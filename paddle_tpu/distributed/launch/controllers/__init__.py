"""Launch controllers (reference: distributed/launch/controllers/ —
controller.py ControllerBase/Controller/ControleMode, collective.py
CollectiveController/CollectiveElasticController, ps.py PSController,
master.py Master/HTTPMaster/ETCDMaster, watcher.py Watcher).

A controller builds this node's Pod (one Container per worker) with the
bootstrap env and deploys/watches it. Node discovery runs through the
HTTP KV master (utils.KVServer — no etcd dependency; ETCDMaster gates
on etcd3's presence honestly).
"""
from __future__ import annotations

import json
import os
import sys
import time

from paddle_tpu.distributed.launch.context import Context, Status
from paddle_tpu.distributed.launch.job import Container, Job, Pod

__all__ = ["init", "ControleMode", "ControllerBase", "Controller",
           "CollectiveController", "CollectiveElasticController",
           "PSController", "IPUController", "Master", "HTTPMaster",
           "ETCDMaster", "Watcher"]


class ControleMode:   # sic — reference spelling (controller.py:27)
    COLLECTIVE = "collective"
    PS = "ps"
    IPU = "ipu"
    RPC = "rpc"


class Master:
    """Node-discovery store base (reference master.py:27)."""

    MAIN = "main"
    STANDBY = "standby"
    PATICIPANT = "participant"   # sic

    def __init__(self, ctx):
        self.ctx = ctx
        self.server = None
        self.initialized = False
        self.endpoint = None

    def stop(self):
        if self.server is not None:
            self.server.stop()
            self.server = None

    def set_status(self, status):
        pass

    def get_status(self):
        return None

    @classmethod
    def factory(cls, ctx):
        if (ctx.args.master or "").startswith("etcd://"):
            return ETCDMaster(ctx)
        return HTTPMaster(ctx)


class HTTPMaster(Master):
    """KVServer-backed barrier/sync (reference master.py:65): rank 0
    hosts the store; every node writes its endpoint under the job
    prefix and polls until nnodes are present."""

    def lazy_init(self):
        if self.initialized:
            return
        self.role = Master.PATICIPANT
        if self.ctx.args.master:
            self.endpoint = self.ctx.args.master
            ip, port = self.endpoint.split(":")
            if ip in ("127.0.0.1", self.ctx.node.ip):
                from paddle_tpu.distributed.launch.utils import KVServer
                try:
                    self.server = KVServer(int(port))
                    self.server.start()
                    self.role = Master.MAIN
                except OSError:
                    pass  # another process on this host owns it
        else:
            from paddle_tpu.distributed.launch.utils import KVServer
            port = self.ctx.node.get_free_port()
            self.endpoint = f"{self.ctx.node.ip}:{port}"
            self.server = KVServer(port)
            self.server.start()
            self.role = Master.MAIN
        from paddle_tpu.distributed.launch.utils import KVClient
        self.client = KVClient(self.endpoint)
        self.initialized = True

    def sync_peers(self, prefix, key, value, size, rank=-1):
        """Register value under prefix and wait for all `size` peers;
        returns (sorted peer values, this rank)."""
        if size < 2:
            return [value], 0
        self.lazy_init()
        self.client.wait_server_ready()
        self.client.put(f"{prefix}/{key}", value)
        deadline = time.time() + 300
        while time.time() < deadline:
            peers = self.client.get_prefix(prefix)
            if len(peers) >= size:
                values = [v for _, v in sorted(peers.items())]
                me = values.index(value) if rank < 0 else rank
                return values, me
            time.sleep(0.5)
        raise TimeoutError(f"sync_peers: {len(peers)}/{size} after 300s")


class ETCDMaster(Master):
    """etcd-backed master (reference master.py:177); requires etcd3,
    which this build does not bundle — constructing without it fails
    with the dependency named."""

    def __init__(self, ctx):
        super().__init__(ctx)
        try:
            import etcd3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "ETCDMaster needs the etcd3 package; use an http:// "
                "master (HTTPMaster) in this environment") from e


class ControllerBase:
    def __init__(self, ctx):
        self.ctx = ctx
        self.master = Master.factory(ctx)
        self.job = Job(jid=ctx.args.job_id,
                       mode=ctx.args.run_mode,
                       nnodes=ctx.args.nnodes or "1")
        self.pod = Pod()
        self.join_server = None

    def deploy_pod(self):
        self.ctx.status.run()
        self.pod.deploy()

    def run(self):
        self.build_job()
        self.build_pod()
        self.deploy_pod()
        self.watch()

    def watch(self):
        while True:
            status = self.pod.status()
            if status in (Status.COMPLETED, Status.FAILED):
                if status == Status.FAILED:
                    self.pod.stop()
                    self.ctx.status.fail()
                    return False
                self.ctx.status.complete()
                return True
            time.sleep(1)

    def stop(self, sigint=15):
        self.master.stop()
        self.pod.stop(sigint)

    def finalize(self):
        self.pod.join()
        self.master.stop()
        sys.exit(self.pod.exit_code)

    def signal_handler(self, sigint, frame):
        self.stop(sigint)
        sys.exit(sigint)


class Controller(ControllerBase):
    """Adds entrypoint/env plumbing (reference controller.py:161)."""

    def build_job(self):
        self.ctx.logger.info(f"Job {self.job.id}: mode={self.job.mode} "
                             f"replicas={self.job.replicas}")

    def entrypoint(self, ctx=None):
        ctx = ctx or self.ctx
        entry = [sys.executable, "-u", ctx.args.training_script]
        entry += list(ctx.args.training_script_args or [])
        return entry

    def new_container(self, entrypoint=None, envs=None, out=None,
                      err=None):
        c = Container(entrypoint=entrypoint or self.entrypoint(),
                      env=self.ctx.get_envs())
        c.update_env(envs or {})
        c.outfile = out
        c.errfile = err
        return c

    def add_container(self, container=None, entrypoint=None, envs=None,
                      log_file=None, is_init=False):
        if container is None:
            log_path = (os.path.join(self.ctx.args.log_dir, log_file)
                        if self.ctx.args.log_dir and log_file else None)
            container = self.new_container(entrypoint=entrypoint,
                                           envs=envs, out=log_path,
                                           err=log_path)
        if is_init:
            self.pod.add_init_container(container)
        else:
            self.pod.add_container(container)

    def pod_replicas(self):
        if self.ctx.args.nproc_per_node:
            return int(self.ctx.args.nproc_per_node)
        # one process per HOST on TPU (single-controller SPMD)
        return 1


class CollectiveController(Controller):
    """Build the node's pod for a collective job (reference
    collective.py:21): discover peers through the master, then spawn
    workers with the PADDLE_*/JAX bootstrap env."""

    @classmethod
    def enable(cls, ctx):
        return True

    def build_pod(self):
        replicas = self.pod_replicas()
        data = json.dumps({
            "name": self.pod.name,
            "rank": self.ctx.args.rank if self.ctx.args.rank is not None
            else -1,
            "replicas": replicas,
            "dtype": self.ctx.node.device.dtype,
            "candidate": f"{self.ctx.node.ip}:"
                         f"{self.ctx.node.get_free_port()}",
        })
        nnodes = self.job.replicas
        peer_list, _ = self.master.sync_peers(
            f"/{self.job.id}/info", self.pod.name, data, nnodes)
        peers = [json.loads(p) for p in peer_list]
        # sync_peers orders by pod NAME (random); when users pinned
        # explicit --rank values the coordinator (global rank 0) must be
        # the rank-0 NODE, so re-order by the reported ranks — name
        # order only when no rank was pinned anywhere
        if all(pr["rank"] >= 0 for pr in peers):
            peers.sort(key=lambda pr: pr["rank"])
        rank = next(i for i, pr in enumerate(peers)
                    if pr["name"] == self.pod.name)
        self.pod.rank = rank
        global_size = sum(pr["replicas"] for pr in peers)
        rank_offset = sum(pr["replicas"] for pr in peers[:rank])
        coordinator = peers[0]["candidate"]
        endpoints = [p["candidate"] for p in peers]
        for i in range(replicas):
            e = {
                "PADDLE_MASTER": coordinator,
                "PADDLE_NNODES": str(global_size),
                "PADDLE_TRAINER_ID": str(rank_offset + i),
                "PADDLE_TRAINERS_NUM": str(global_size),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
                "PADDLE_LOCAL_RANK": str(i),
                "JAX_COORDINATOR_ADDRESS": coordinator,
            }
            self.add_container(envs=e, log_file=f"workerlog.{i}")
        return True


class CollectiveElasticController(CollectiveController):
    """Elastic collective (reference collective.py:184): watch + rebuild
    on failure while the job's nnodes range allows it."""

    @classmethod
    def enable(cls, ctx):
        return bool(ctx.args.master)

    def run(self):
        self.build_job()
        attempts = max(1, self.job.replicas_max - self.job.replicas_min
                       + 1)
        for _ in range(attempts):
            self.pod.reset()
            self.build_pod()
            self.deploy_pod()
            if self.watch():
                return
            self.ctx.logger.warning("pod failed; elastic restart")
        self.ctx.status.fail()


class PSController(Controller):
    """PS-mode pod: server containers then trainer containers
    (reference ps.py:21); the PS tables themselves live in
    distributed/ps.py."""

    @classmethod
    def enable(cls, ctx):
        return ctx.args.run_mode == ControleMode.PS

    def build_pod(self):
        servers = int(os.environ.get("PADDLE_PSERVER_NUM", 1))
        trainers = self.pod_replicas()
        for i in range(servers):
            self.add_container(
                envs={"PADDLE_ROLE": "PSERVER",
                      "PADDLE_PSERVER_ID": str(i)},
                log_file=f"serverlog.{i}")
        for i in range(trainers):
            self.add_container(
                envs={"PADDLE_ROLE": "TRAINER",
                      "PADDLE_TRAINER_ID": str(i)},
                log_file=f"workerlog.{i}")
        return True


class IPUController(CollectiveController):
    """IPU hardware is out of scope for a TPU-native runtime."""

    @classmethod
    def enable(cls, ctx):
        return False

    def build_pod(self):
        raise RuntimeError("IPU is not supported on the TPU runtime")


class Watcher:
    """Resource watcher (reference watcher.py:22): samples device info
    into the log dir (when set) and keeps a BOUNDED in-memory window —
    a multi-day job must not grow the controller without limit."""

    MAX_SAMPLES = 720   # ~1h at the 5s cadence

    def __init__(self, ctx):
        self.ctx = ctx
        self.stopped = False
        self.samples = []
        self._log_path = (os.path.join(ctx.args.log_dir, "devicelog")
                          if ctx.args.log_dir else None)
        import threading
        self.proc = threading.Thread(target=self.watch, daemon=True)
        self.proc.start()

    def watch(self):
        from paddle_tpu.distributed.launch.utils import get_gpu_info
        while not self.stopped:
            info = get_gpu_info()
            self.samples.append(info)
            if len(self.samples) > self.MAX_SAMPLES:
                del self.samples[:len(self.samples) - self.MAX_SAMPLES]
            if self._log_path:
                try:
                    with open(self._log_path, "a") as fh:
                        fh.write(json.dumps(
                            [i.dict() for i in info]) + "\n")
                except OSError:
                    pass
            time.sleep(5)

    def stop(self):
        self.stopped = True


class RpcController(CollectiveController):
    """Reference controllers/rpc.py: launch workers for the
    paddle.distributed.rpc programming model. The pod build is the
    collective one (workers get the master endpoint env, which is
    exactly what distributed/rpc.py's TCP rendezvous consumes)."""

    @classmethod
    def enable(cls, ctx):
        return getattr(ctx.args, "run_mode", None) == "rpc"


def init(ctx):
    """Pick the controller for the context (reference
    controllers/__init__.py:33)."""
    for cls in (PSController, RpcController, CollectiveElasticController,
                CollectiveController):
        if cls.enable(ctx):
            return cls(ctx)
    raise RuntimeError("no controller enabled for this context")
