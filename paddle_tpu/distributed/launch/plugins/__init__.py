"""Launch plugins (reference: distributed/launch/plugins/__init__.py
log/process_args/collective_compatible + test.py's smoke-train trio)."""
from __future__ import annotations

__all__ = []


def log(ctx):
    ctx.print()


def process_args(ctx):
    argdev = ctx.args.devices
    if argdev:
        for d in argdev.split(","):
            if d not in ctx.node.device.labels:
                ctx.logger.error(
                    f"device {d} not in node inventory "
                    f"{ctx.node.device.labels}")


def collective_compatible(ctx):
    """Honor legacy PADDLE_TRAINER_ENDPOINTS env (reference behavior):
    derive master + nnodes from the endpoint list."""
    if "PADDLE_TRAINER_ENDPOINTS" in ctx.envs:
        eps = ctx.envs["PADDLE_TRAINER_ENDPOINTS"].split(",")
        hosts = {h.split(":")[0] for h in eps}
        ctx.args.master = eps[0] if ":" in eps[0] else f"{eps[0]}:6768"
        ctx.args.nnodes = str(len(hosts))


enabled_plugins = [collective_compatible, process_args, log]


# ---- test.py trio (reference plugins/test.py): a ready-made smoke
# train for validating a fresh multi-host setup ------------------------
from paddle_tpu.io import Dataset  # noqa: E402


class RandomDataset(Dataset):
    def __init__(self, num_samples):
        self.num_samples = num_samples

    def __getitem__(self, idx):
        import numpy as np
        rng = np.random.RandomState(idx)
        image = rng.random(size=(3, 224, 224)).astype("float32")
        label = rng.randint(0, 100, (1,)).astype("int64")
        return image, label

    def __len__(self):
        return self.num_samples


def optimizer_setting(parameter_list=None):
    import paddle_tpu as paddle
    return paddle.optimizer.Momentum(
        learning_rate=0.01, momentum=0.9, parameters=parameter_list)


def train_resnet(epoch=1, batch_size=8, batch_num=2):
    """Tiny distributed ResNet run (reference plugins/test.py:56)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.io import DataLoader
    from paddle_tpu.vision.models import resnet18

    fleet.init(is_collective=True)
    model = resnet18(num_classes=100)
    opt = optimizer_setting(model.parameters())
    opt = fleet.distributed_optimizer(opt)
    model = fleet.distributed_model(model)
    loader = DataLoader(RandomDataset(batch_num * batch_size),
                        batch_size=batch_size, shuffle=True,
                        drop_last=True)
    losses = []
    for _ in range(epoch):
        model.train()
        for img, label in loader:
            out = model(img)
            loss = paddle.nn.functional.cross_entropy(out,
                                                      label.reshape([-1]))
            loss.backward()
            opt.step()
            model.clear_gradients()
            losses.append(float(loss.numpy()))
    return losses
