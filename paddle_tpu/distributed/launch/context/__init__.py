"""Launch context (reference: distributed/launch/context/__init__.py
Context + node.py Node + device.py Device/DeviceType + resource.py,
status.py, event.py).

The context gathers CLI args, PADDLE_* env, and the node's device
inventory; controllers consume it to build the pod.
"""
from __future__ import annotations

import argparse
import logging
import os
import socket

__all__ = ["Context", "Node", "Device", "DeviceType", "Event", "Resource",
           "Status", "fetch_envs"]


class DeviceType:
    CPU = "cpu"
    GPU = "gpu"
    XPU = "xpu"
    NPU = "npu"
    IPU = "ipu"
    TPU = "tpu"


class Device:
    """Node-local accelerator inventory (reference context/device.py).
    Detection prefers TPU_VISIBLE_CHIPS, then live jax devices, then
    cpu."""

    def __init__(self, dtype=None, count=1, memory="", labels=None):
        self.dtype = dtype
        self.count = count
        self.memory = memory
        self.labels = labels or []

    @classmethod
    def detect_device(cls):
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible is not None:
            labels = [x for x in visible.split(",") if x.strip() != ""]
            return cls(DeviceType.TPU, len(labels), labels=labels)
        try:
            import jax
            devs = jax.local_devices()
            dtype = (DeviceType.TPU if devs and devs[0].platform == "tpu"
                     else DeviceType.CPU)
            return cls(dtype, len(devs),
                       labels=[str(d.id) for d in devs])
        except Exception:
            return cls(DeviceType.CPU, 1, labels=["0"])

    def get_selected_device_key(self):
        return {DeviceType.TPU: "TPU_VISIBLE_CHIPS",
                DeviceType.GPU: "CUDA_VISIBLE_DEVICES"}.get(
                    self.dtype, "CPU_NUM")

    def get_selected_devices(self, devices=""):
        if devices:
            return [str(x) for x in devices.split(",")]
        return [str(x) for x in self.labels]


class Node:
    """This host (reference context/node.py): ip + device inventory +
    free-port allocation."""

    def __init__(self):
        self.ip = self._get_host_ip()
        self.device = Device.detect_device()
        self.free_ports = []

    @staticmethod
    def _get_host_ip():
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    def get_free_port(self):
        from paddle_tpu.distributed.utils import find_free_ports
        port = sorted(find_free_ports(1))[0]
        self.free_ports.append(port)
        return port


class Status:
    UNINIT = "uninit"
    READY = "ready"
    RUNNING = "running"
    FAILED = "failed"
    TERMINATING = "terminating"
    RESTARTING = "restarting"
    UNKNOWN = "unknown"
    COMPLETED = "completed"

    def __init__(self):
        self._current_status = self.UNINIT

    def current(self):
        return self._current_status

    def is_running(self):
        return self._current_status == self.RUNNING

    def is_restarting(self):
        return self._current_status == self.RESTARTING

    def is_done(self):
        return self._current_status in (self.COMPLETED, self.FAILED)

    def run(self):
        self._current_status = self.RUNNING

    def fail(self):
        self._current_status = self.FAILED

    def complete(self):
        self._current_status = self.COMPLETED

    def restart(self):
        self._current_status = self.RESTARTING

    def done(self):
        self._current_status = self.COMPLETED


class Event:
    def __init__(self, kind="status", message="", fatal=False):
        self.kind = kind
        self.message = message
        self.fatal = fatal


class Resource:
    def __init__(self, devices=None):
        self.devices = devices or []


def fetch_envs():
    """Full environment snapshot minus proxies (reference context copies
    os.environ; workers NEED PATH/HOME/PYTHONPATH/LD_LIBRARY_PATH — a
    prefix-filtered env would strand every spawned trainer)."""
    env = dict(os.environ)
    env.pop("http_proxy", None)
    env.pop("https_proxy", None)
    return env


def parse_args(argv=None):
    """THE launch CLI — one parser shared by `python -m ...launch`
    (launch/__init__.py main) and Context, so the flag surface cannot
    drift between the two."""
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch",
                                allow_abbrev=False)
    p.add_argument("--master", default=None,
                   help="coordinator host:port (rank 0)")
    p.add_argument("--nnodes", type=str, default=None,
                   help="node count N, or elastic range N:M")
    p.add_argument("--rank", type=int, default=None,
                   help="this node's rank")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("--log_level", default="INFO")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--devices", "--gpus", default=None)
    p.add_argument("--ips", default=None)
    p.add_argument("--legacy", action="store_true")
    p.add_argument("--watchdog-timeout", type=float, default=None)
    p.add_argument("training_script", nargs="?", default=None)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_known_args(argv)


class Context:
    """Everything a controller needs (reference context/__init__.py:24):
    args + env snapshot + node inventory + status + logger."""

    def __init__(self, enable_plugin=True, argv=None):
        self.args, self.unknown_args = parse_args(argv)
        self.envs = fetch_envs()
        self.node = Node()
        self.status = Status()
        self.logger = self.get_logger()
        self.events = []
        if enable_plugin:
            self._enable_plugin()

    def get_envs(self):
        return self.envs.copy()

    def set_envs(self, env=None):
        self.envs.update({k: v for k, v in (env or {}).items()
                          if isinstance(v, str)})

    def is_legacy_mode(self):
        return bool(self.args.legacy)

    def get_logger(self, level=logging.INFO):
        logger = logging.getLogger("LAUNCH")
        logger.setLevel(getattr(logging,
                                str(self.args.log_level).upper(), level))
        if not logger.handlers:
            ch = logging.StreamHandler()
            ch.setFormatter(logging.Formatter(
                fmt="%(name)s %(levelname)s %(asctime)s %(message)s"))
            logger.addHandler(ch)
        return logger

    def print(self):
        self.logger.info("-----------  Configuration  ------------------")
        for arg, value in sorted(vars(self.args).items()):
            self.logger.info("%s: %s", arg, value)
        self.logger.info("----------------------------------------------")

    def _enable_plugin(self):
        from paddle_tpu.distributed.launch import plugins
        for pl in plugins.enabled_plugins:
            pl(self)

    def continous_log(self):
        return str(self.args.log_level).upper() in ("DEBUG", "ERROR")
