"""Launcher utilities (reference: distributed/launch/utils/ —
kv_server.py KVHandler/KVServer/PKVServer, kv_client.py KVClient,
process_context.py ProcessContext, nvsmi.py Info/get_gpu_info/
get_gpu_process).

The KV server/client are the master's node-discovery store (real
threaded HTTP, stdlib only). nvsmi's GPU probes map to the TPU device
inventory.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from http.server import HTTPServer, SimpleHTTPRequestHandler

__all__ = ["KVHandler", "KVServer", "PKVServer", "KVClient", "Info",
           "ProcessContext", "get_gpu_info", "get_gpu_process"]


class KVHandler(SimpleHTTPRequestHandler):
    """GET returns the whole scope as JSON; PUT/POST writes a key;
    DELETE removes it (reference kv_server.py:24)."""

    def do_GET(self):
        with self.server.kv_lock:
            scope = {k: v for k, v in self.server.kv.items()
                     if k.startswith(self.path)}
        body = json.dumps({k: v.decode() if isinstance(v, bytes) else v
                           for k, v in scope.items()}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(n).decode() if n else ""
        with self.server.kv_lock:
            self.server.kv[self.path] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    do_POST = do_PUT

    def do_DELETE(self):
        with self.server.kv_lock:
            self.server.kv.pop(self.path, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *args):
        pass  # silent


class KVServer(HTTPServer):
    def __init__(self, port):
        super().__init__(("", port), KVHandler)
        self.kv = {}
        self.kv_lock = threading.Lock()
        self.stopped = False

    def start(self):
        self.listen_thread = threading.Thread(target=self.serve_forever,
                                              daemon=True)
        self.listen_thread.start()

    def stop(self):
        self.shutdown()
        self.listen_thread.join()
        self.server_close()
        self.stopped = True


class PKVServer:
    """KVServer in a separate PROCESS (reference kv_server.py:91) so it
    survives the controller's GIL-heavy phases."""

    def __init__(self, port):
        self._port = port
        self._proc = None

    def start(self):
        code = ("from paddle_tpu.distributed.launch.utils import KVServer;"
                f"s = KVServer({self._port}); s.start(); "
                "import time\n"
                "while True: time.sleep(3600)")
        self._proc = subprocess.Popen([sys.executable, "-c", code])

    def stop(self):
        if self._proc:
            self._proc.terminate()
            self._proc.wait(10)

    @property
    def started(self):
        return self._proc is not None and self._proc.poll() is None


class KVClient:
    """stdlib http client for KVServer (reference kv_client.py)."""

    def __init__(self, endpoint="localhost:2379"):
        self.endpoint = (endpoint if endpoint.startswith("http")
                         else f"http://{endpoint}")

    def _request(self, method, key, value=None):
        import urllib.request
        key = key if key.startswith("/") else "/" + key
        req = urllib.request.Request(
            self.endpoint + key, method=method,
            data=value.encode() if value is not None else None)
        try:
            with urllib.request.urlopen(req, timeout=3) as r:
                return r.read().decode()
        except OSError:
            return None

    def put(self, key, value):
        return self._request("PUT", key, value) is not None

    def get(self, key):
        out = self._request("GET", key)
        if out is None:
            return ""
        data = json.loads(out)
        key = key if key.startswith("/") else "/" + key
        return data.get(key, "")

    def get_prefix(self, key):
        out = self._request("GET", key)
        return json.loads(out) if out else {}

    def delete(self, key):
        return self._request("DELETE", key) is not None

    def wait_server_ready(self, timeout=30):
        import time
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._request("GET", "/") is not None:
                return True
            time.sleep(0.3)
        return False


class Info:
    """Device info record (reference nvsmi.py Info)."""

    def __init__(self, **kwargs):
        self.__dict__.update(kwargs)

    def __repr__(self):
        return json.dumps(self.__dict__)

    def json(self):
        return json.dumps(self.__dict__)

    def dict(self):
        return dict(self.__dict__)


def get_gpu_info(query=None):
    """Accelerator inventory (reference nvsmi.get_gpu_info shells to
    nvidia-smi): reports the node's TPU/CPU devices."""
    from paddle_tpu.distributed.launch.context import Device
    dev = Device.detect_device()
    return [Info(index=str(i), uuid=f"{dev.dtype}-{i}",
                 utilization_gpu="", memory_total="", memory_used="")
            for i in range(dev.count)]


def get_gpu_process(query=None):
    """Processes bound to local accelerators: the TPU claim is
    single-process, so at most this process."""
    from paddle_tpu.distributed.launch.context import Device
    dev = Device.detect_device()
    if dev.dtype == "tpu":
        return [Info(pid=os.getpid(), process_name=sys.argv[0],
                     gpu_uuid="tpu-0")]
    return []


class ProcessContext:
    """One worker subprocess with env + log redirection (reference
    process_context.py)."""

    def __init__(self, cmd, env=None, out=None, err=None,
                 preexec_fn=None, shell=False):
        self._cmd = cmd if isinstance(cmd, list) else cmd.split()
        self._env = dict(env or os.environ)
        self._out = out
        self._err = err
        self._preexec_fn = preexec_fn
        self._shell = shell
        self._proc = None
        self._out_fh = self._err_fh = None

    def start(self):
        if self._out:
            os.makedirs(os.path.dirname(self._out) or ".", exist_ok=True)
            self._out_fh = open(self._out, "ab")
        if self._err and self._err != self._out:
            self._err_fh = open(self._err, "ab")
        self._proc = subprocess.Popen(
            self._cmd, env=self._env, shell=self._shell,
            stdout=self._out_fh, stderr=self._err_fh or self._out_fh,
            preexec_fn=self._preexec_fn)
        return self._proc

    def alive(self):
        return self._proc is not None and self._proc.poll() is None

    def exit_code(self):
        return self._proc.poll() if self._proc else None

    def wait(self, timeout=None):
        if self._proc:
            try:
                return self._proc.wait(timeout)
            except subprocess.TimeoutExpired:
                return None

    def terminate(self, force=False):
        if self._proc is None:
            return True
        if self._proc.poll() is None:
            self._proc.kill() if force else self._proc.terminate()
        for fh in (self._out_fh, self._err_fh):
            if fh:
                fh.close()
        return self._proc.poll() is not None


# ---- reference launch/utils/nvsmi.py surface (no nvidia in a TPU
# deployment: honest empty results, never a crash) ----
def has_nvidia_smi():
    import shutil
    return shutil.which("nvidia-smi") is not None


def _smi_rows(fields):
    """Shell out to nvidia-smi when present; [] otherwise (every TPU
    host) — consistent with has_nvidia_smi."""
    if not has_nvidia_smi():
        return []
    import subprocess
    try:
        out = subprocess.run(
            ["nvidia-smi", f"--query-gpu={','.join(fields)}",
             "--format=csv,noheader,nounits"],
            capture_output=True, text=True, timeout=10).stdout
    except Exception:
        return []
    rows = []
    for line in out.strip().splitlines():
        vals = [v.strip() for v in line.split(",")]
        rows.append(dict(zip(fields, vals)))
    return rows


def query_smi(query=None, query_type="gpu", index=None, dtype=None):
    """Reference nvsmi.query_smi: list of per-GPU info dicts."""
    return _smi_rows(query or ["index", "uuid", "name",
                               "memory.total", "memory.used"])


def get_gpu_util(index=None):
    return _smi_rows(["index", "utilization.gpu", "memory.total",
                      "memory.used"])


def get_gpu_info(index=None):
    return _smi_rows(["index", "uuid", "driver_version", "name"])
