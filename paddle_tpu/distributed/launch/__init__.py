"""Multi-host launcher package (reference: python/paddle/distributed/
launch/ — the `python -m paddle.distributed.launch` CLI with its
context/job/controllers/plugins/utils architecture).

TPU-native design: one process per HOST (JAX single-controller-per-host
SPMD), not one per chip; coordination over DCN via jax.distributed
(coordinator address + process id), after which jax.devices() spans
every chip in the pod slice and the global Mesh covers them. The
controller architecture is preserved for scripts that drive it — the
CollectiveController builds the node-local pod and spawns worker
processes with the bootstrap env; `launch()` is the in-process fast
path a TPU host normally takes.

Usage:
  python -m paddle_tpu.distributed.launch \
      --master 10.0.0.1:8476 --nnodes 4 --rank $NODE_RANK train.py ...

Env fallbacks: PADDLE_MASTER, PADDLE_NNODES, PADDLE_TRAINER_ID
(reference names), or JAX TPU metadata autodetection when none given.
"""
from __future__ import annotations

import os
import runpy
import sys

from paddle_tpu.distributed.launch import (  # noqa: F401
    context,
    controllers,
    job,
    plugins,
    utils,
)


def _from_env(args):
    if args.master is None:
        args.master = os.environ.get("PADDLE_MASTER")
    if args.nnodes is None:
        v = os.environ.get("PADDLE_NNODES")
        args.nnodes = int(v) if v else None
    if args.rank is None:
        v = os.environ.get("PADDLE_TRAINER_ID")
        args.rank = int(v) if v else None
    return args


def _rendezvous(master, nnodes, rank):
    """``jax.distributed.initialize`` under a deadline + seeded-backoff
    retry (PR 6 RetryPolicy): a transient coordinator (slow boot, port
    not yet bound, packet loss) is retried; a fleet that never forms
    raises a machine-readable ``resilience.fleet.CollectiveTimeout``
    instead of the historical behavior (hang for jax's 300s default,
    then an opaque backend error).  Budget knobs:
    ``PTPU_RENDEZVOUS_TIMEOUT_S`` (per-attempt, default 120) and
    ``PTPU_RENDEZVOUS_ATTEMPTS`` (default 3)."""
    import random
    import time

    import jax

    from paddle_tpu.resilience.fleet import CollectiveTimeout, _env_float
    from paddle_tpu.resilience.retry import RetryPolicy, compute_backoff

    timeout_s = _env_float("PTPU_RENDEZVOUS_TIMEOUT_S", 120.0)
    attempts = int(_env_float("PTPU_RENDEZVOUS_ATTEMPTS", 3))
    policy = RetryPolicy(max_attempts=max(1, attempts), backoff=0.5,
                         multiplier=2.0, max_backoff=10.0, jitter=0.5)
    rng = random.Random(rank or 0)
    t0 = time.monotonic()
    last = None
    use_timeout = True
    for attempt in range(policy.max_attempts):
        try:
            if use_timeout:
                try:
                    jax.distributed.initialize(
                        coordinator_address=master,
                        num_processes=nnodes, process_id=rank,
                        initialization_timeout=max(1, int(timeout_s)))
                    return
                except TypeError:
                    # older jax without initialization_timeout: fall
                    # through to the plain call — still INSIDE this
                    # attempt's failure handling, so a coordinator
                    # slow-boot there retries like any other attempt
                    use_timeout = False
            jax.distributed.initialize(coordinator_address=master,
                                       num_processes=nnodes,
                                       process_id=rank)
            return
        except Exception as e:
            last = e
            # a half-initialized global_state would make the retry a
            # "called twice" error, not a reconnect
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            if attempt + 1 < policy.max_attempts:
                time.sleep(compute_backoff(policy, attempt, rng))
    waited = time.monotonic() - t0
    if waited < 0.5 * timeout_s:
        # attempts failed FAST — a config error (bad address, port in
        # use, version mismatch), not a slow fleet.  Supervisors treat
        # CollectiveTimeout as transient-and-retryable; mislabeling a
        # permanently misconfigured launch would restart it forever
        raise RuntimeError(
            f"launch rendezvous to {master!r} failed "
            f"{policy.max_attempts}x in {waited:.1f}s (well under the "
            f"{timeout_s:.0f}s budget) — a configuration error, not a "
            f"timeout") from last
    raise CollectiveTimeout(
        "launch.rendezvous", key=master, waited_s=waited,
        timeout_s=timeout_s * policy.max_attempts) from last


def launch(master=None, nnodes=None, rank=None, watchdog_timeout=None):
    """Initialize multi-host coordination; returns (process_index,
    process_count). Safe to call on single host (no-op init)."""
    import jax
    if master is not None and (nnodes is None or nnodes < 2):
        raise ValueError(
            f"--master {master} given but nnodes={nnodes}: a multi-host "
            "launch needs --nnodes >= 2 (or PADDLE_NNODES); refusing to "
            "silently train standalone")
    if master is not None and nnodes and nnodes > 1:
        _rendezvous(master, nnodes, rank)
        # agree on the per-run launch id (namespaces every coordination
        # key) and reap the whole namespace on clean exit — an aborted
        # run leaves only keys the NEXT run can never collide with.
        # The reap rides the finalize() done-barrier: a bare delete at
        # first-exiter atexit would strand slower peers mid-collective
        import atexit

        from paddle_tpu.resilience import fleet
        fleet._ensure_launch_id()
        atexit.register(fleet.finalize)
    else:
        try:
            jax.distributed.initialize()  # TPU metadata autodetect
        except Exception:
            pass  # single host, no coordination service
    from paddle_tpu.distributed.mesh import ensure_mesh
    ensure_mesh()
    if watchdog_timeout:
        from paddle_tpu.distributed import elastic
        # beats arrive via elastic.notify_progress() from Optimizer.step(),
        # so the script needs no changes for the watchdog to see progress
        launch._elastic = elastic.install_manager(
            elastic.ElasticManager(timeout=watchdog_timeout))
    return jax.process_index(), jax.process_count()


def main(argv=None):
    # the ONE CLI lives in context.parse_args (shared with Context)
    from paddle_tpu.distributed.launch.context import parse_args
    args, unknown = parse_args(argv)
    if unknown:
        raise SystemExit(f"unknown launch arguments: {unknown}")
    if args.training_script is None:
        raise SystemExit("missing training script")
    # "N" or elastic "N:M" — the in-process fast path uses the minimum
    if args.nnodes is not None and ":" in str(args.nnodes):
        args.nnodes = str(args.nnodes).split(":")[0]
    args.nnodes = int(args.nnodes) if args.nnodes else None
    args = _from_env(args)

    launch(args.master, args.nnodes, args.rank, args.watchdog_timeout)
    sys.argv = [args.training_script] + list(args.training_script_args)
    runpy.run_path(args.training_script, run_name="__main__")


if __name__ == "__main__":
    main()
