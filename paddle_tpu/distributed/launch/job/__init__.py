"""Job model (reference: distributed/launch/job/ — job.py Job/JobMode,
pod.py Pod/PodSepc, container.py Container, status.py Status): a Job is
N Pods (one per node), each Pod runs Containers (worker processes)."""
from __future__ import annotations

import os
import uuid

from paddle_tpu.distributed.launch.context import Status  # noqa: F401

__all__ = ["Job", "JobMode", "Pod", "PodSepc", "Container", "Status"]


class JobMode:
    COLLECTIVE = "collective"
    PS = "ps"
    HETER = "heter"


class Job:
    def __init__(self, jid="default", mode=JobMode.COLLECTIVE, nnodes="1"):
        self.mode = mode
        self.id = jid
        self.replicas = 0
        # "N" or "N:M" elastic range (reference job.py)
        nnodes = str(nnodes)
        if ":" in nnodes:
            lo, hi = nnodes.split(":")
            self.replicas_min, self.replicas_max = int(lo), int(hi)
        else:
            self.replicas_min = self.replicas_max = int(nnodes or 1)
        self.replicas = self.replicas_min

    @property
    def elastic(self):
        return self.replicas_min < self.replicas_max


class Container:
    """One worker process + its env/log plumbing (reference
    container.py:23), backed by utils.ProcessContext."""

    def __init__(self, entrypoint="", rank=-1, env=None):
        self._entrypoint = entrypoint
        self._rank = rank
        self._env = dict(env or {})
        self._proc = None
        self._out = None
        self._err = None
        self._log_handler = None

    @property
    def entrypoint(self):
        return self._entrypoint

    @entrypoint.setter
    def entrypoint(self, ep):
        self._entrypoint = ep

    @property
    def rank(self):
        return self._rank

    @rank.setter
    def rank(self, r):
        self._rank = r

    @property
    def outfile(self):
        return self._out

    @outfile.setter
    def outfile(self, out):
        self._out = out

    @property
    def errfile(self):
        return self._err

    @errfile.setter
    def errfile(self, err):
        self._err = err

    def update_env(self, env=None, **kwargs):
        self._env.update({k: v for k, v in (env or {}).items()
                          if isinstance(v, str)})
        self._env.update({k: v for k, v in kwargs.items()
                          if isinstance(v, str)})

    @property
    def env(self):
        return self._env

    def start(self):
        from paddle_tpu.distributed.launch.utils import ProcessContext
        if self._proc and self._proc.alive():
            return True
        self._proc = ProcessContext(self._entrypoint, env=self._env,
                                    out=self._out, err=self._err)
        self._proc.start()
        return True

    def terminate(self, force=False):
        if self._proc:
            return self._proc.terminate(force)

    def wait(self, timeout=None):
        if self._proc:
            return self._proc.wait(timeout)

    @property
    def exit_code(self):
        return self._proc.exit_code() if self._proc else None

    def status(self):
        if self._proc is None:
            return Status.UNINIT
        if self._proc.alive():
            return Status.RUNNING
        if self._proc.exit_code() == 0:
            return Status.COMPLETED
        return Status.FAILED

    def __str__(self):
        return (f"Container rank {self._rank} status {self.status()} "
                f"cmd {self._entrypoint}")


class PodSepc:   # sic — the reference spells it this way (pod.py:23)
    def __init__(self):
        self._name = "".join(str(uuid.uuid4()).split("-")[:1])
        self._containers = []
        self._init_containers = []
        self._resource = None
        self._status = None
        self._rank = -1
        self._replicas = 0


class Pod(PodSepc):
    """This node's worker group (reference pod.py:43)."""

    def __init__(self):
        super().__init__()
        self._status = Status()

    def __str__(self):
        return (f"Pod: {self.name}, replicas {self.replicas}, "
                f"status {self.status()}")

    @property
    def name(self):
        return self._name

    @property
    def replicas(self):
        return self._replicas

    @replicas.setter
    def replicas(self, r):
        self._replicas = r

    @property
    def rank(self):
        return self._rank

    @rank.setter
    def rank(self, r):
        self._rank = r

    @property
    def containers(self):
        return self._containers

    def add_container(self, c):
        c.rank = len(self._containers)
        self._containers.append(c)

    @property
    def init_containers(self):
        return self._init_containers

    def add_init_container(self, c):
        c.rank = len(self._init_containers)
        self._init_containers.append(c)

    def deploy(self):
        for i in self._init_containers:
            i.start()
            i.wait()
        for c in self._containers:
            c.start()
        self._status.run()

    def stop(self, sigint=15, timeout=None):
        for c in self._containers:
            c.terminate(force=(sigint == 9))
        if timeout:
            self.join(timeout)

    def join(self, timeout=None):
        for c in self._containers:
            c.wait(timeout)

    def status(self):
        statuses = [c.status() for c in self._containers]
        if not statuses:
            return Status.UNINIT
        if any(s == Status.FAILED for s in statuses):
            return Status.FAILED
        if all(s == Status.COMPLETED for s in statuses):
            return Status.COMPLETED
        if any(s == Status.RUNNING for s in statuses):
            return Status.RUNNING
        return Status.READY

    def failed_container(self):
        return [c for c in self._containers
                if c.status() == Status.FAILED]

    @property
    def exit_code(self):
        for c in self._containers:
            if c.exit_code not in (0, None):
                return c.exit_code
        return 0

    def reset(self):
        self._containers = []
        self._init_containers = []
