"""PS table/coordinator vocabulary (reference: distributed/ps/
the_one_ps.py Table:620 / BarrierTable:634 / DenseTable:836 /
TensorTable / GlobalStepTable, and ps/coordinator.py ClientSelector /
Coordinator / FLClient*).

The live parameter-server machinery here is distributed/ps.py's
host-RAM SparseTable (jit-safe callbacks + the native C++ pstable
kernels). These classes carry the reference's table-descriptor
vocabulary for code that constructs PS topologies explicitly; dense
parameters need no table at all (they live on-device, sharded by XLA),
so DenseTable fronts a plain host buffer and BarrierTable wraps the
collective barrier. The FL (federated-learning) client/coordinator
surface is declared but gated: this runtime has no cross-silo
transport, and pretending otherwise would train silently wrong.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Table", "BarrierTable", "DenseTable", "TensorTable",
           "GlobalStepTable", "ClientSelectorBase", "ClientSelector",
           "Coordinator", "FLClientBase", "FLClient"]


class Table:
    """Table descriptor base (reference the_one_ps.py:620)."""

    def __init__(self):
        self.id = -1
        self.table_class = None
        self.shard_num = 256
        self.type = None
        self.tensor = None

    def _set(self, table_proto=None):
        return None


class BarrierTable(Table):
    """Trainer barrier as a table op (reference the_one_ps.py:634);
    here the mesh's collective barrier IS the implementation."""

    def __init__(self, idx=0, trainer_num=1):
        super().__init__()
        self.id = idx
        self.table_class = "BarrierTable"
        self.trainer_num = trainer_num

    def barrier(self):
        from paddle_tpu.distributed.collective import barrier
        return barrier()


class DenseTable(Table):
    """Dense parameter block on the server (reference
    the_one_ps.py:836). Dense params live on-device under XLA sharding;
    this front keeps a host mirror for reference-style pull/push."""

    def __init__(self, idx=0, shape=None, dtype="float32"):
        super().__init__()
        self.id = idx
        self.table_class = "MemoryDenseTable"
        self._buf = np.zeros(shape or (0,), dtype)

    def pull(self):
        return self._buf.copy()

    def push(self, grad, lr=1.0):
        self._buf -= lr * np.asarray(grad, self._buf.dtype)
        return self._buf


class TensorTable(Table):
    def __init__(self, idx=0, tensor=None):
        super().__init__()
        self.id = idx
        self.table_class = "TensorTable"
        self.tensor = tensor


class GlobalStepTable(TensorTable):
    def __init__(self, idx=0):
        super().__init__(idx)
        self.table_class = "GlobalStepTable"
        self._step = 0

    def increment(self, n=1):
        self._step += n
        return self._step


class ClientSelectorBase:
    """FL client sampling base (reference coordinator.py:49)."""

    def __init__(self, clients_info=None):
        self.clients_info = dict(clients_info or {})

    def select(self):
        raise NotImplementedError


class ClientSelector(ClientSelectorBase):
    """Random fraction selector (reference coordinator.py:80)."""

    def __init__(self, clients_info=None, fraction=1.0, seed=0):
        super().__init__(clients_info)
        self.fraction = fraction
        self._rng = np.random.default_rng(seed)

    def select(self):
        ids = sorted(self.clients_info)
        k = max(1, int(round(len(ids) * self.fraction))) if ids else 0
        return list(self._rng.choice(ids, size=k, replace=False)) \
            if k else []


def _no_fl_transport(*a, **kw):
    raise RuntimeError(
        "federated-learning coordination needs a cross-silo RPC "
        "transport, which this TPU runtime does not ship; "
        "the in-datacenter PS path is distributed/ps.py")


class FLClientBase:
    """Declared FL client surface (reference coordinator.py FLClientBase)
    — constructing is allowed (for topology code), communicating is an
    explicit capability error."""

    def __init__(self):
        self.strategy = None

    connect = _no_fl_transport
    push_fl_client_info_sync = _no_fl_transport
    pull_fl_strategy = _no_fl_transport


class FLClient(FLClientBase):
    pass


class Coordinator:
    """FL round coordinator (reference coordinator.py:356): selection
    works (it is pure policy); transport is gated like FLClient."""

    def __init__(self, ps_hosts=None):
        self.ps_hosts = ps_hosts
        self.selector = None

    def start_coordinator(self, self_endpoint=None, trainer_endpoints=None):
        self.selector = ClientSelector(
            {i: {"endpoint": e}
             for i, e in enumerate(trainer_endpoints or [])})
        return self.selector

    def make_fl_strategy(self):
        if self.selector is None:
            raise RuntimeError("start_coordinator first")
        return {cid: "JOIN" for cid in self.selector.select()}
