"""paddle.distributed.communication.stream parity.  XLA schedules its
own compute/collective streams; the stream-targeted variants are the
same collectives (reference stream/*.py route to the same kernels with a
stream hint the TPU compiler derives itself)."""
from paddle_tpu.distributed.collective import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all_single,
    alltoall,
    alltoall_single,
    broadcast,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
