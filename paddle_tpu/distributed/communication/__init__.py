"""paddle.distributed.communication parity (reference:
python/paddle/distributed/communication/): the collective API lives in
paddle_tpu.distributed.collective; this namespace re-exports it plus the
`stream` variants.  On XLA there is no separate comm stream to schedule
onto — the compiler owns stream assignment — so stream.* == the sync
forms."""
from paddle_tpu.distributed.collective import (  # noqa: F401
    Group,
    P2POp,
    ReduceOp,
    destroy_process_group,
    get_group,
    all_gather,
    all_reduce,
    all_to_all_single,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    irecv,
    isend,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from paddle_tpu.distributed.communication import stream  # noqa: F401

# int8-payload gradient sync (EQuARX-class; see PAPERS.md)
from paddle_tpu.distributed.quantized_collective import (  # noqa: E402,F401
    quantized_all_reduce_mean,
    quantized_all_reduce_sum,
)


def is_initialized():
    """Reference: distributed/communication/group.py:132 (lazy import —
    the flag lives on the distributed package root)."""
    import paddle_tpu.distributed as dist
    return dist.is_initialized()
