"""RPC framework (reference: python/paddle/distributed/rpc/rpc.py —
init_rpc :73, rpc_sync :141, rpc_async :179, shutdown :270,
get_worker_info :299).

The reference rides brpc through the C++ core.  Here each worker runs a
threaded `multiprocessing.connection.Listener` service; the master
endpoint is a tiny in-process rendezvous server that exchanges
(name, ip, port) triples, after which calls go worker<->worker directly.
Callables are sent by qualified name (module:qualname) and re-resolved
on the callee — the wire format carries DATA, never code objects, so a
malicious peer can at most call functions already importable there.
Thread-based futures back rpc_async.
"""
from __future__ import annotations

import importlib
import threading
import time
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor
from multiprocessing.connection import Client, Listener

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = 30.0
_AUTH = b"paddle_tpu_rpc"

_state = {
    "self": None,          # WorkerInfo
    "workers": {},         # name -> WorkerInfo
    "listener": None,
    "serve_thread": None,
    "pool": None,          # serves INCOMING requests
    "client_pool": None,   # runs OUTBOUND rpc_async calls — separate so
                           # self-calls/cycles can't starve the server side
    "master": None,        # _Rendezvous if this rank hosts it
    "shutdown": False,
}


def _fn_ref(fn):
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", None)
    if not mod or not qual or "<locals>" in qual or "<lambda>" in qual:
        raise ValueError(
            "rpc can only ship module-level functions (sent by qualified "
            "name, resolved on the callee — closures/lambdas have no "
            "importable name)")
    return f"{mod}:{qual}"


def _resolve(ref):
    mod, qual = ref.split(":", 1)
    obj = importlib.import_module(mod)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


# ------------------------------------------------------------ rendezvous
class _Rendezvous:
    """Master-endpoint name exchange: collects world_size WorkerInfos,
    then hands the full table to every caller."""

    def __init__(self, host, port, world_size):
        self._infos = {}
        self._cv = threading.Condition()
        self._world = world_size
        self._listener = Listener((host, port), authkey=_AUTH)
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        handlers = []
        for _ in range(self._world):
            try:
                conn = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            handlers.append(t)

    def _handle(self, conn):
        info = WorkerInfo(*conn.recv())
        with self._cv:
            self._infos[info.name] = info
            self._cv.notify_all()
            self._cv.wait_for(lambda: len(self._infos) >= self._world)
        conn.send(sorted(self._infos.values(), key=lambda w: w.rank))
        conn.close()

    def close(self):
        try:
            self._listener.close()
        except OSError:
            pass


# ------------------------------------------------------------ worker side
def _serve_loop(listener, pool):
    while not _state["shutdown"]:
        try:
            conn = listener.accept()
        except OSError:
            return

        def handle(c):
            try:
                msg = c.recv()
                if msg[0] == "call":
                    _, ref, args, kwargs = msg
                    try:
                        out = _resolve(ref)(*args, **(kwargs or {}))
                        c.send(("ok", out))
                    except Exception as e:  # ship the error, not a hang
                        c.send(("err", f"{type(e).__name__}: {e}"))
                elif msg[0] == "bye":
                    c.send(("ok", None))
            except EOFError:
                pass
            finally:
                c.close()

        pool.submit(handle, conn)


def _my_ip(master_host):
    """Address other workers can dial: loopback stays loopback for a
    local master; otherwise the interface that routes to the master."""
    import socket
    if master_host in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((master_host, 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Join the RPC world: rank 0's process hosts the rendezvous at
    master_endpoint; every worker starts its service and learns every
    other worker's endpoint.

    Launcher contract (reference rpc/internal.py + launch rpc mode):
    unset arguments fall back to the PADDLE_MASTER_ENDPOINT /
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM environment the launch
    controllers export, so `paddle.distributed.launch --run_mode rpc`
    workers need only call init_rpc(name)."""
    import os
    if master_endpoint is None:
        master_endpoint = os.environ.get(
            "PADDLE_MASTER_ENDPOINT", os.environ.get("PADDLE_MASTER"))
    if rank is None and os.environ.get("PADDLE_TRAINER_ID"):
        rank = int(os.environ["PADDLE_TRAINER_ID"])
    if world_size is None and os.environ.get("PADDLE_TRAINERS_NUM"):
        world_size = int(os.environ["PADDLE_TRAINERS_NUM"])
    host, port = (master_endpoint or "127.0.0.1:29500").split(":")
    port = int(port)
    rank = 0 if rank is None else rank
    world_size = 1 if world_size is None else world_size

    if rank == 0:
        _state["master"] = _Rendezvous(host, port, world_size)

    my_ip = _my_ip(host)
    listener = Listener(("", 0), authkey=_AUTH)  # reachable from peers
    my_port = listener.address[1]
    _state["listener"] = listener
    _state["pool"] = ThreadPoolExecutor(max_workers=8)
    _state["client_pool"] = ThreadPoolExecutor(max_workers=8)
    _state["serve_thread"] = threading.Thread(
        target=_serve_loop, args=(listener, _state["pool"]), daemon=True)
    _state["shutdown"] = False
    _state["serve_thread"].start()

    me = WorkerInfo(name, rank, my_ip, my_port)
    _state["self"] = me
    deadline = time.time() + _DEFAULT_RPC_TIMEOUT
    while True:
        try:
            conn = Client((host, port), authkey=_AUTH)
            break
        except ConnectionError:
            if time.time() > deadline:
                raise
            time.sleep(0.05)
    conn.send(tuple(me))
    infos = conn.recv()
    conn.close()
    _state["workers"] = {w.name: WorkerInfo(*w) for w in infos}
    return me


def _invoke(to, fn, args, kwargs, timeout):
    w = _state["workers"].get(to)
    if w is None:
        raise RuntimeError(f"unknown rpc worker {to!r}; known: "
                           f"{sorted(_state['workers'])}")
    conn = Client((w.ip, w.port), authkey=_AUTH)
    try:
        conn.send(("call", _fn_ref(fn), tuple(args or ()),
                   dict(kwargs or {})))
        if timeout is not None and timeout > 0 and not conn.poll(timeout):
            raise TimeoutError(
                f"rpc to {to!r} timed out after {timeout}s")
        status, payload = conn.recv()
    finally:
        conn.close()
    if status == "err":
        raise RuntimeError(f"rpc to {to!r} failed remotely: {payload}")
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking remote call; returns the result."""
    return _invoke(to, fn, args, kwargs, timeout)


class _FutureWrapper:
    """reference FutureWrapper surface (.wait) over a stdlib Future —
    wrapping instead of monkey-patching Future keeps the stdlib class
    untouched."""

    def __init__(self, fut):
        self._fut = fut

    def wait(self, timeout=None):
        return self._fut.result(timeout)

    def result(self, timeout=None):
        return self._fut.result(timeout)

    def done(self):
        return self._fut.done()


def rpc_async(to, fn, args=None, kwargs=None, timeout=_DEFAULT_RPC_TIMEOUT):
    """Non-blocking remote call; returns a future with .wait()/.result()."""
    return _FutureWrapper(
        _state["client_pool"].submit(_invoke, to, fn, args, kwargs,
                                     timeout))


def shutdown():
    """Synchronize and tear the service down."""
    _state["shutdown"] = True
    if _state["listener"] is not None:
        try:
            _state["listener"].close()
        except OSError:
            pass
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=False)
    if _state["client_pool"] is not None:
        _state["client_pool"].shutdown(wait=False)
    if _state["master"] is not None:
        _state["master"].close()
    for k in ("self", "listener", "serve_thread", "pool", "client_pool",
              "master"):
        _state[k] = None
    _state["workers"] = {}


def get_worker_info(name):
    return _state["workers"][name]


def get_all_worker_infos():
    return sorted(_state["workers"].values(), key=lambda w: w.rank)


def get_current_worker_info():
    return _state["self"]
