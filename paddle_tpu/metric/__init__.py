"""Metrics. Reference: python/paddle/metric/metrics.py."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pv = np.asarray(pred)
        lv = np.asarray(label)
        if lv.ndim == pv.ndim and lv.shape[-1] == 1:
            lv = lv[..., 0]
        order = np.argsort(-pv, axis=-1)[..., :self.maxk]
        correct = order == lv[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        cv = np.asarray(correct)
        batch = cv.shape[0] if cv.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += cv[..., :k].sum()
            self.count[i] += batch
        out = self.total / np.maximum(self.count, 1)
        return out[0] if len(self.topk) == 1 else out

    def accumulate(self):
        out = self.total / np.maximum(self.count, 1)
        return float(out[0]) if len(self.topk) == 1 else [float(o) for o in out]

    def name(self):
        return [f"{self._name}_top{k}" for k in self.topk] \
            if len(self.topk) > 1 else [self._name]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(preds) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self._name = name or "auc"
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        pv = np.asarray(preds)
        if pv.ndim == 2:
            pv = pv[:, -1]
        lv = np.asarray(labels).reshape(-1)
        bins = np.round(pv * self.num_thresholds).astype(np.int64)
        for b, l in zip(bins, lv):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate from the highest threshold down
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    pv = np.asarray(input._value if isinstance(input, Tensor) else input)
    lv = np.asarray(label._value if isinstance(label, Tensor) else label)
    if lv.ndim == pv.ndim and lv.shape[-1] == 1:
        lv = lv[..., 0]
    order = np.argsort(-pv, axis=-1)[..., :k]
    corr = (order == lv[..., None]).any(axis=-1).mean()
    return Tensor(np.asarray(corr, np.float32))
