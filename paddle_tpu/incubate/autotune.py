"""paddle.incubate.autotune parity (reference:
python/paddle/incubate/autotune.py set_config :23).

The reference's three tuners map onto TPU realities:
- kernel: XLA's autotuner already exhaustively selects conv/matmul
  algorithms during compilation — the knob records intent and is
  otherwise satisfied by construction.
- layout: recorded and surfaced via get_config(); models opt in through
  data_format="NHWC" (vision models support it; the bench uses it).
- dataloader: ENABLED by default here — the native C++ loader sizes its
  prefetch ring from the config's dataloader settings.
"""
from __future__ import annotations

import json

__all__ = ["set_config", "get_config"]

_config = {
    "kernel": {"enable": True, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": True},
}


def set_config(config=None):
    """dict, JSON-file path, or None (enable everything)."""
    global _config
    if config is None:
        for section in _config.values():
            section["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise TypeError("config must be None, a dict, or a JSON file path")
    for key, value in config.items():
        if key not in _config:
            raise ValueError(
                f"unknown autotune section {key!r}; valid: "
                f"{sorted(_config)}")
        if not isinstance(value, dict):
            raise TypeError(f"autotune section {key!r} must be a dict")
        _config[key].update(value)


def get_config():
    return {k: dict(v) for k, v in _config.items()}
