"""paddle.incubate.nn.layer parity namespace (reference:
python/paddle/incubate/nn/layer/) — the layer classes live in
paddle_tpu.incubate.nn; this package re-exports them at the reference's
submodule path."""
from paddle_tpu.incubate.nn import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedFeedForward,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedTransformer,
    FusedTransformerEncoderLayer,
)
