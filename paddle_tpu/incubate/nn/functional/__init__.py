"""Fused transformer functionals.

Reference parity: python/paddle/incubate/nn/functional/fused_transformer.py
— fused_feedforward (:31), fused_multi_head_attention (:462); plus
fused_linear (fused_matmul_bias.py).

TPU-native design: the reference lowers these to monolithic CUDA fused
kernels (fused_feedforward_op / fused_attention_op). Here the fusion is
split between the XLA compiler (bias+activation+dropout+residual
epilogues fuse into the matmuls automatically under jit) and Pallas
kernels for the pieces XLA fuses poorly: the layer norms run on the
fused Pallas norm kernel and the attention core takes the flash-attention
kernel whenever no additive mask / attention dropout forces the dense
path. Same math, compiler-placed fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import next_key
from paddle_tpu.ops.pallas.norm import fused_layer_norm

__all__ = ["fused_feedforward", "fused_multi_head_attention",
           "fused_linear", "fused_bias_dropout_residual_layer_norm"]


def _v(x):
    return x._value if isinstance(x, Tensor) else (
        None if x is None else jnp.asarray(x))


def _t(x):
    if x is None or isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x))


def _apply_opt(fn, *args):
    """apply() over a mixed (Tensor | None) argument list: None slots are
    closed over; Tensor slots participate in autograd."""
    tensors = [a for a in args if a is not None]
    idx = [i for i, a in enumerate(args) if a is not None]

    def wrapper(*vals):
        full = [None] * len(args)
        for i, v in zip(idx, vals):
            full[i] = v
        return fn(*full)

    return apply(wrapper, *tensors)


def _dropout_val(v, rate, training, mode):
    if not training or rate == 0.0:
        return v if mode == "upscale_in_train" else v * (1.0 - rate)
    keep = jax.random.bernoulli(next_key(), 1.0 - rate,
                                v.shape).astype(v.dtype)
    if mode == "upscale_in_train":
        return v * keep / (1.0 - rate)
    return v * keep


def _ln(v, scale, bias, eps):
    return fused_layer_norm(v, scale, bias, eps).astype(v.dtype)


_ACTS = {
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
}


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """matmul + bias add in one op (reference fused_matmul_bias)."""
    def fn(xv, wv, bv):
        w = wv.T if transpose_weight else wv
        y = xv @ w
        return y if bv is None else y + bv

    return _apply_opt(fn, _t(x), _t(weight), _t(bias))


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Transformer FFN block: (pre-)LN -> linear1 -> act -> dropout1 ->
    linear2 -> dropout2 -> (+residual) -> (post-)LN.
    Reference: incubate/nn/functional/fused_transformer.py:31."""
    act = _ACTS[activation]

    def fn(xv, w1, w2, b1, b2, g1, be1, g2, be2):
        residual = xv
        out = _ln(xv, g1, be1, ln1_epsilon) if pre_layer_norm else xv
        out = out @ w1
        if b1 is not None:
            out = out + b1
        out = _dropout_val(act(out), dropout1_rate, training, mode)
        out = out @ w2
        if b2 is not None:
            out = out + b2
        out = _dropout_val(out, dropout2_rate, training, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, g2, be2, ln2_epsilon)
        return out

    return _apply_opt(fn, _t(x), _t(linear1_weight), _t(linear2_weight),
                      _t(linear1_bias), _t(linear2_bias), _t(ln1_scale),
                      _t(ln1_bias), _t(ln2_scale), _t(ln2_bias))


def _convert_mask(mask, dtype):
    if mask.dtype == jnp.bool_:
        return jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
    if jnp.issubdtype(mask.dtype, jnp.integer):
        return jnp.where(mask != 0, 0.0, jnp.finfo(jnp.float32).min)
    return mask.astype(jnp.float32)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, name=None):
    """Fused self-attention block. qkv_weight: [3, n_head, head_dim,
    embed_dim]; qkv_bias: [3, n_head, head_dim]. With cache_kv
    ([2, b, n, s_cache, d]) returns (out, updated_cache).
    Reference: incubate/nn/functional/fused_transformer.py:462.

    The attention core runs the Pallas flash kernel when no additive mask
    and no attention dropout require materializing the score matrix."""
    has_cache = cache_kv is not None

    def fn(xv, qkvw, lw, pg, pb, g, b, qkvb, lb, cache, mask):
        bsz, s, e = xv.shape
        _, n, hd, _ = qkvw.shape
        residual = xv
        out = _ln(xv, pg, pb, pre_ln_epsilon) if pre_layer_norm else xv
        w = qkvw.reshape(3 * n * hd, e)
        qkv = out @ w.T                                  # [b, s, 3nd]
        if qkvb is not None:
            qkv = qkv + qkvb.reshape(3 * n * hd)
        qkv = qkv.reshape(bsz, s, 3, n, hd)
        qkv = jnp.moveaxis(qkv, 2, 0)                    # [3, b, s, n, d]
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in qkv)   # [b, n, s, d]
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=2)
            v = jnp.concatenate([cache[1], v], axis=2)
            new_cache = jnp.stack([k, v], axis=0)
        scale = float(hd) ** -0.5
        drop_attn = training and attn_dropout_rate > 0.0
        if mask is None and not drop_attn:
            from paddle_tpu.ops.pallas.flash_attention import (
                flash_attention_bhsd)
            ctx = flash_attention_bhsd(q, k, v, causal=False, scale=scale)
        else:
            s_qk = (q * scale) @ jnp.swapaxes(k, -1, -2)
            if mask is not None:
                s_qk = s_qk + _convert_mask(mask, s_qk.dtype)
            p = jax.nn.softmax(s_qk.astype(jnp.float32), axis=-1) \
                .astype(xv.dtype)
            p = _dropout_val(p, attn_dropout_rate, training, mode)
            ctx = p @ v
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(bsz, s, n * hd)
        out = ctx @ lw
        if lb is not None:
            out = out + lb
        out = _dropout_val(out, dropout_rate, training, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, g, b, ln_epsilon)
        if cache is not None:
            return out, new_cache
        return out

    return _apply_opt(fn, _t(x), _t(qkv_weight), _t(linear_weight),
                      _t(pre_ln_scale), _t(pre_ln_bias), _t(ln_scale),
                      _t(ln_bias), _t(qkv_bias), _t(linear_bias),
                      _t(cache_kv) if has_cache else None, _t(attn_mask))


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """LayerNorm(residual + dropout(x + bias)) as ONE fused region
    (reference incubate/nn/functional/fused_transformer.py
    fused_bias_dropout_residual_layer_norm): the bias add, dropout and
    residual add are elementwise epilogues XLA fuses into the layer-norm
    reduction (the Pallas fused_layer_norm kernel on TPU)."""
    use_dropout = training and dropout_rate > 0.0
    key = next_key() if use_dropout else None

    def fn(xv, rv, bv, sv, bbv, *rest):
        h = xv if bv is None else xv + bv
        if use_dropout:
            keep = jax.random.bernoulli(
                jax.random.wrap_key_data(rest[0]), 1.0 - dropout_rate,
                h.shape)
            if mode == "upscale_in_train":
                h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
            else:
                h = jnp.where(keep, h, 0.0)
        elif mode == "downscale_in_infer" and dropout_rate > 0.0:
            # eval-time scaling for the non-upscaled train mode, matching
            # _dropout_val's convention
            h = h * (1.0 - dropout_rate)
        h = h + rv
        d = h.shape[-1]
        flat = h.reshape(-1, d)
        out = fused_layer_norm(flat, sv, bbv, ln_epsilon)
        return out.reshape(h.shape)

    args = [_t(x), _t(residual), _t(bias), _t(ln_scale), _t(ln_bias)]
    if use_dropout:
        return apply(fn, *args, Tensor(jax.random.key_data(key)))
    return apply(fn, *args)
