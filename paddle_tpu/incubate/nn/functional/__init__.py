"""Fused transformer functionals.

Reference parity: python/paddle/incubate/nn/functional/fused_transformer.py
— fused_feedforward (:31), fused_multi_head_attention (:462); plus
fused_linear (fused_matmul_bias.py).

TPU-native design: the reference lowers these to monolithic CUDA fused
kernels (fused_feedforward_op / fused_attention_op). Here the fusion is
split between the XLA compiler (bias+activation+dropout+residual
epilogues fuse into the matmuls automatically under jit) and Pallas
kernels for the pieces XLA fuses poorly: the layer norms run on the
fused Pallas norm kernel and the attention core takes the flash-attention
kernel whenever no additive mask / attention dropout forces the dense
path. Same math, compiler-placed fusion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import next_key
from paddle_tpu.ops.pallas.norm import fused_layer_norm

__all__ = ["fused_feedforward", "fused_multi_head_attention",
           "fused_linear", "fused_bias_dropout_residual_layer_norm",
           "fused_matmul_bias", "fused_multi_transformer"]


def _v(x):
    return x._value if isinstance(x, Tensor) else (
        None if x is None else jnp.asarray(x))


def _t(x):
    if x is None or isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x))


def _apply_opt(fn, *args):
    """apply() over a mixed (Tensor | None) argument list: None slots are
    closed over; Tensor slots participate in autograd."""
    tensors = [a for a in args if a is not None]
    idx = [i for i, a in enumerate(args) if a is not None]

    def wrapper(*vals):
        full = [None] * len(args)
        for i, v in zip(idx, vals):
            full[i] = v
        return fn(*full)

    return apply(wrapper, *tensors)


def _dropout_val(v, rate, training, mode):
    if not training or rate == 0.0:
        return v if mode == "upscale_in_train" else v * (1.0 - rate)
    keep = jax.random.bernoulli(next_key(), 1.0 - rate,
                                v.shape).astype(v.dtype)
    if mode == "upscale_in_train":
        return v * keep / (1.0 - rate)
    return v * keep


def _ln(v, scale, bias, eps):
    return fused_layer_norm(v, scale, bias, eps).astype(v.dtype)


_ACTS = {
    "relu": jax.nn.relu,
    # tanh approximation: the reference's FUSED kernels use GeluFunctor
    # (paddle/phi/kernels/funcs/functors.h:129, explicitly the tanh
    # form) even though plain F.gelu defaults to erf — jax.nn.gelu's
    # default matches the fused convention
    "gelu": jax.nn.gelu,
}


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """matmul + bias add in one op (reference fused_matmul_bias)."""
    def fn(xv, wv, bv):
        w = wv.T if transpose_weight else wv
        y = xv @ w
        return y if bv is None else y + bv

    return _apply_opt(fn, _t(x), _t(weight), _t(bias))


def fused_matmul_bias(x, y, bias=None, transpose_x=False,
                      transpose_y=False, name=None):
    """matmul(+transposes) + bias in one op (reference
    incubate/nn/functional/fused_matmul_bias.py:21 — cublasLt epilogue
    fusion there; XLA fuses the bias add into the MXU matmul here)."""
    def fn(xv, yv, bv):
        a = jnp.swapaxes(xv, -1, -2) if transpose_x else xv
        b = jnp.swapaxes(yv, -1, -2) if transpose_y else yv
        out = a @ b
        return out if bv is None else out + bv

    return _apply_opt(fn, _t(x), _t(y), _t(bias))


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Transformer FFN block: (pre-)LN -> linear1 -> act -> dropout1 ->
    linear2 -> dropout2 -> (+residual) -> (post-)LN.
    Reference: incubate/nn/functional/fused_transformer.py:31."""
    act = _ACTS[activation]

    def fn(xv, w1, w2, b1, b2, g1, be1, g2, be2):
        residual = xv
        out = _ln(xv, g1, be1, ln1_epsilon) if pre_layer_norm else xv
        out = out @ w1
        if b1 is not None:
            out = out + b1
        out = _dropout_val(act(out), dropout1_rate, training, mode)
        out = out @ w2
        if b2 is not None:
            out = out + b2
        out = _dropout_val(out, dropout2_rate, training, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, g2, be2, ln2_epsilon)
        return out

    return _apply_opt(fn, _t(x), _t(linear1_weight), _t(linear2_weight),
                      _t(linear1_bias), _t(linear2_bias), _t(ln1_scale),
                      _t(ln1_bias), _t(ln2_scale), _t(ln2_bias))


def _convert_mask(mask, dtype):
    if mask.dtype == jnp.bool_:
        return jnp.where(mask, 0.0, jnp.finfo(jnp.float32).min)
    if jnp.issubdtype(mask.dtype, jnp.integer):
        return jnp.where(mask != 0, 0.0, jnp.finfo(jnp.float32).min)
    return mask.astype(jnp.float32)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, name=None):
    """Fused self-attention block. qkv_weight: [3, n_head, head_dim,
    embed_dim]; qkv_bias: [3, n_head, head_dim]. With cache_kv
    ([2, b, n, s_cache, d]) returns (out, updated_cache).
    Reference: incubate/nn/functional/fused_transformer.py:462.

    The attention core runs the Pallas flash kernel when no additive mask
    and no attention dropout require materializing the score matrix."""
    has_cache = cache_kv is not None

    def fn(xv, qkvw, lw, pg, pb, g, b, qkvb, lb, cache, mask):
        bsz, s, e = xv.shape
        _, n, hd, _ = qkvw.shape
        residual = xv
        out = _ln(xv, pg, pb, pre_ln_epsilon) if pre_layer_norm else xv
        w = qkvw.reshape(3 * n * hd, e)
        qkv = out @ w.T                                  # [b, s, 3nd]
        if qkvb is not None:
            qkv = qkv + qkvb.reshape(3 * n * hd)
        qkv = qkv.reshape(bsz, s, 3, n, hd)
        qkv = jnp.moveaxis(qkv, 2, 0)                    # [3, b, s, n, d]
        q, k, v = (jnp.swapaxes(t, 1, 2) for t in qkv)   # [b, n, s, d]
        if cache is not None:
            k = jnp.concatenate([cache[0], k], axis=2)
            v = jnp.concatenate([cache[1], v], axis=2)
            new_cache = jnp.stack([k, v], axis=0)
        scale = float(hd) ** -0.5
        drop_attn = training and attn_dropout_rate > 0.0
        if mask is None and not drop_attn:
            from paddle_tpu.ops.pallas.flash_attention import (
                flash_attention_bhsd)
            ctx = flash_attention_bhsd(q, k, v, causal=False, scale=scale)
        else:
            s_qk = (q * scale) @ jnp.swapaxes(k, -1, -2)
            if mask is not None:
                s_qk = s_qk + _convert_mask(mask, s_qk.dtype)
            p = jax.nn.softmax(s_qk.astype(jnp.float32), axis=-1) \
                .astype(xv.dtype)
            p = _dropout_val(p, attn_dropout_rate, training, mode)
            ctx = p @ v
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(bsz, s, n * hd)
        out = ctx @ lw
        if lb is not None:
            out = out + lb
        out = _dropout_val(out, dropout_rate, training, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _ln(out, g, b, ln_epsilon)
        if cache is not None:
            return out, new_cache
        return out

    return _apply_opt(fn, _t(x), _t(qkv_weight), _t(linear_weight),
                      _t(pre_ln_scale), _t(pre_ln_bias), _t(ln_scale),
                      _t(ln_bias), _t(qkv_bias), _t(linear_bias),
                      _t(cache_kv) if has_cache else None, _t(attn_mask))


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, pre_caches=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """The serving-path fused stack: N decoder blocks in ONE op.

    Reference: incubate/nn/functional/fused_transformer.py:828
    (fused_multi_transformer — the monolithic CUDA kernel serving loops
    call once per model). TPU-native: the whole stack traces into a
    single tape op / XLA region, so under jit the per-layer
    LN→QKV→attention→proj→FFN chain fuses layer-to-layer with no Python
    dispatch between blocks — the same role the CUDA kernel plays.

    Weight layouts match the reference: qkv_weights [3, n_head, head_dim,
    embed] when trans_qkvw (else [embed, 3, n_head, head_dim]); cache_kvs
    entries are STATIC [2, bsz, n_head, max_seq_len, head_dim] buffers —
    prefill (time_step=None) writes positions [0, s), decode
    (time_step=t) writes position t and attends over [0, t] with a
    static-shape mask (no dynamic shapes ever reach XLA). time_step may
    also be a [bsz] VECTOR for ragged decode: each sequence writes and
    attends at its own length, so continuation batching serves mixed-
    length requests without re-padding. Returns out, or
    (out, updated_cache_kvs) when cache_kvs is given — updated
    functionally, not in place. ring_id is the reference's NCCL group
    id; tensor parallelism here comes from weight shardings (GSPMD), so
    it is accepted and ignored.
    """
    L = len(qkv_weights)
    act = _ACTS[activation]
    has_cache = cache_kvs is not None

    def _opt_list(lst):
        return [None] * L if lst is None else [_t(w) for w in lst]

    flat = ([_t(x), _t(attn_mask),
             _t(time_step) if time_step is not None else None]
            + [_t(w) for w in ln_scales] + [_t(w) for w in ln_biases]
            + [_t(w) for w in qkv_weights] + _opt_list(qkv_biases)
            + [_t(w) for w in linear_weights] + _opt_list(linear_biases)
            + [_t(w) for w in ffn_ln_scales] + [_t(w) for w in ffn_ln_biases]
            + [_t(w) for w in ffn1_weights] + _opt_list(ffn1_biases)
            + [_t(w) for w in ffn2_weights] + _opt_list(ffn2_biases)
            + (_opt_list(cache_kvs) if has_cache else [])
            + (_opt_list(pre_caches) if pre_caches is not None else []))

    def fn(*vals):
        xv, mask, tstep = vals[0], vals[1], vals[2]
        rest = list(vals[3:])

        def take(n):
            out = rest[:n]
            del rest[:n]
            return out

        ln_s, ln_b = take(L), take(L)
        qkvw, qkvb = take(L), take(L)
        lw, lb = take(L), take(L)
        fln_s, fln_b = take(L), take(L)
        w1, b1 = take(L), take(L)
        w2, b2 = take(L), take(L)
        caches = take(L) if has_cache else [None] * L
        pcaches = take(L) if pre_caches is not None else [None] * L

        bsz, s, e = xv.shape
        if trans_qkvw:
            _, n, hd, _ = qkvw[0].shape
        else:
            _, n, hd = qkvw[0].shape[1:]
        scale = float(hd) ** -0.5
        mask_add = None if mask is None else _convert_mask(mask, jnp.float32)

        out = xv
        new_caches = []
        for i in range(L):
            residual = out
            h = _ln(out, ln_s[i], ln_b[i], epsilon) if pre_layer_norm \
                else out
            w = qkvw[i].reshape(3 * n * hd, e).T if trans_qkvw \
                else qkvw[i].reshape(e, 3 * n * hd)
            qkv = h @ w
            if qkvb[i] is not None:
                qkv = qkv + qkvb[i].reshape(3 * n * hd)
            qkv = jnp.moveaxis(qkv.reshape(bsz, s, 3, n, hd), 2, 0)
            q, k, v = (jnp.swapaxes(t_, 1, 2) for t_ in qkv)  # [b,n,s,d]

            kv_mask_extra = None
            if pcaches[i] is not None and tstep is None:
                # prefix keys come FIRST (independent of cache_kvs: a
                # prefix-tuning forward without a decode cache still
                # attends over the prefix); with a cache the concatenated
                # stream is stored so decode offsets line up
                k = jnp.concatenate([pcaches[i][0], k], axis=2)
                v = jnp.concatenate([pcaches[i][1], v], axis=2)
            if caches[i] is not None:
                cache = caches[i]
                max_len = cache.shape[3]
                if tstep is None:                       # prefill
                    cache = cache.at[0, :, :, :k.shape[2]].set(k)
                    cache = cache.at[1, :, :, :v.shape[2]].set(v)
                else:                                   # decode: s == 1
                    if s != 1:
                        raise ValueError(
                            f"decode (time_step given) expects one token "
                            f"per sequence, got seq_len {s}")
                    ts = jnp.reshape(tstep, (-1,)).astype(jnp.int32)
                    if ts.shape[0] not in (1, bsz):
                        raise ValueError(
                            f"time_step must be scalar-like or [batch] "
                            f"({bsz}), got shape {tuple(ts.shape)}")
                    if ts.shape[0] == 1:
                        # uniform decode: one position for the batch
                        t0 = ts[0]
                        cache = jax.lax.dynamic_update_slice(
                            cache, jnp.stack([k, v], 0)[:, :, :, :1],
                            (0, 0, 0, t0, 0))
                        kv_mask_extra = jnp.where(
                            jnp.arange(max_len)[None, None, None, :] <= t0,
                            0.0, jnp.finfo(jnp.float32).min)
                    else:
                        # RAGGED decode (time_step of shape [bsz]): each
                        # sequence writes/attends at its OWN length —
                        # continuation batching without re-padding (the
                        # ragged-attention serving pattern, static shapes)
                        kv_new = jnp.stack([k, v], 0)  # [2, b, n, 1, d]

                        def upd(cache_b, kv_b, t_b):
                            return jax.lax.dynamic_update_slice(
                                cache_b, kv_b, (0, 0, t_b, 0))

                        cache = jax.vmap(upd, in_axes=(1, 1, 0),
                                         out_axes=1)(cache, kv_new, ts)
                        kv_mask_extra = jnp.where(
                            jnp.arange(max_len)[None, None, None, :]
                            <= ts[:, None, None, None],
                            0.0, jnp.finfo(jnp.float32).min)
                    k = cache[0]
                    v = cache[1]
                new_caches.append(cache)

            s_qk = (q * scale) @ jnp.swapaxes(k, -1, -2)
            s_qk = s_qk.astype(jnp.float32)
            if mask_add is not None:
                # applies in decode too (padding masks must keep masking
                # cached positions); the caller provides the right shape,
                # [b, 1, s_q, s_k] — same contract as the reference kernel
                s_qk = s_qk + mask_add
            if kv_mask_extra is not None:
                s_qk = s_qk + kv_mask_extra
            p = jax.nn.softmax(s_qk, axis=-1).astype(xv.dtype)
            p = _dropout_val(p, dropout_rate, training, mode)
            ctx = jnp.swapaxes(p @ v, 1, 2).reshape(bsz, s, n * hd)
            attn_out = ctx @ lw[i]
            if lb[i] is not None:
                attn_out = attn_out + lb[i]
            attn_out = _dropout_val(attn_out, dropout_rate, training, mode)
            if pre_layer_norm:
                out = residual + attn_out
            else:
                out = _ln(residual + attn_out, ln_s[i], ln_b[i], epsilon)

            residual = out
            h = _ln(out, fln_s[i], fln_b[i], epsilon) if pre_layer_norm \
                else out
            h = h @ w1[i]
            if b1[i] is not None:
                h = h + b1[i]
            h = _dropout_val(act(h), dropout_rate, training, mode)
            h = h @ w2[i]
            if b2[i] is not None:
                h = h + b2[i]
            h = _dropout_val(h, dropout_rate, training, mode)
            if pre_layer_norm:
                out = residual + h
            else:
                out = _ln(residual + h, fln_s[i], fln_b[i], epsilon)

        if has_cache:
            return tuple([out] + new_caches)
        return out

    result = _apply_opt(fn, *flat)
    if has_cache:
        return result[0], list(result[1:])
    return result


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """LayerNorm(residual + dropout(x + bias)) as ONE fused region
    (reference incubate/nn/functional/fused_transformer.py
    fused_bias_dropout_residual_layer_norm): the bias add, dropout and
    residual add are elementwise epilogues XLA fuses into the layer-norm
    reduction (the Pallas fused_layer_norm kernel on TPU)."""
    use_dropout = training and dropout_rate > 0.0
    key = next_key() if use_dropout else None

    def fn(xv, rv, bv, sv, bbv, *rest):
        h = xv if bv is None else xv + bv
        if use_dropout:
            keep = jax.random.bernoulli(
                jax.random.wrap_key_data(rest[0]), 1.0 - dropout_rate,
                h.shape)
            if mode == "upscale_in_train":
                h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
            else:
                h = jnp.where(keep, h, 0.0)
        elif mode == "downscale_in_infer" and dropout_rate > 0.0:
            # eval-time scaling for the non-upscaled train mode, matching
            # _dropout_val's convention
            h = h * (1.0 - dropout_rate)
        h = h + rv
        d = h.shape[-1]
        flat = h.reshape(-1, d)
        out = fused_layer_norm(flat, sv, bbv, ln_epsilon)
        return out.reshape(h.shape)

    args = [_t(x), _t(residual), _t(bias), _t(ln_scale), _t(ln_bias)]
    if use_dropout:
        return apply(fn, *args, Tensor(jax.random.key_data(key)))
    return apply(fn, *args)
