"""Fused transformer layers.

Reference parity: python/paddle/incubate/nn/layer/fused_transformer.py —
FusedMultiHeadAttention (:191), FusedFeedForward (:478),
FusedTransformerEncoderLayer (:706). Thin Layer wrappers over the
functionals in incubate.nn.functional (which place the fusion on the XLA
compiler + Pallas kernels instead of the reference's monolithic CUDA
ops).
"""
from __future__ import annotations

from paddle_tpu.incubate.nn import functional  # noqa: F401
from paddle_tpu.incubate.nn.functional import (fused_feedforward,
                                               fused_multi_head_attention)
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward", "FusedLinear",
           "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiTransformer",
           "FusedTransformer"]


class FusedMultiHeadAttention(Layer):
    """Reference incubate/nn/layer/fused_transformer.py:191."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim > 0 and num_heads > 0
        assert embed_dim % num_heads == 0
        head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self._dropout_rate = dropout_rate
        self._attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            shape=[3, num_heads, head_dim, embed_dim], attr=qkv_weight_attr,
            default_initializer=I.XavierUniform())
        self.qkv_bias = None
        if qkv_bias_attr is not False:
            self.qkv_bias = self.create_parameter(
                shape=[3, num_heads, head_dim], attr=qkv_bias_attr,
                is_bias=True)
        self.linear_weight = self.create_parameter(
            shape=[embed_dim, embed_dim], attr=linear_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear_bias = None
        if linear_bias_attr is not False:
            self.linear_bias = self.create_parameter(
                shape=[embed_dim], attr=linear_bias_attr, is_bias=True)
        ones = I.Constant(1.0)
        zeros = I.Constant(0.0)
        self.pre_ln_scale = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_scale_attr,
            default_initializer=ones)
        self.pre_ln_bias = self.create_parameter(
            shape=[embed_dim], attr=pre_ln_bias_attr,
            default_initializer=zeros, is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=ln_scale_attr,
            default_initializer=ones)
        self.ln_bias = self.create_parameter(
            shape=[embed_dim], attr=ln_bias_attr,
            default_initializer=zeros, is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        return fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            attn_dropout_rate=self._attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training)


class FusedFeedForward(Layer):
    """Reference incubate/nn/layer/fused_transformer.py:478."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert d_model > 0 and dim_feedforward > 0
        self._normalize_before = normalize_before
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self._activation = activation
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            shape=[d_model, dim_feedforward], attr=linear1_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear1_bias = self.create_parameter(
            shape=[dim_feedforward], attr=linear1_bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            shape=[dim_feedforward, d_model], attr=linear2_weight_attr,
            default_initializer=I.XavierUniform())
        self.linear2_bias = self.create_parameter(
            shape=[d_model], attr=linear2_bias_attr, is_bias=True)
        ones = I.Constant(1.0)
        zeros = I.Constant(0.0)
        self.ln1_scale = self.create_parameter(
            shape=[d_model], attr=ln1_scale_attr, default_initializer=ones)
        self.ln1_bias = self.create_parameter(
            shape=[d_model], attr=ln1_bias_attr, default_initializer=zeros,
            is_bias=True)
        self.ln2_scale = self.create_parameter(
            shape=[d_model], attr=ln2_scale_attr, default_initializer=ones)
        self.ln2_bias = self.create_parameter(
            shape=[d_model], attr=ln2_bias_attr, default_initializer=zeros,
            is_bias=True)

    def forward(self, src, cache=None):
        return fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias, self.ln1_scale,
            self.ln1_bias, self.ln2_scale, self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._activation, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self._normalize_before, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Reference incubate/nn/layer/fused_transformer.py:706."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None \
            else attn_dropout_rate
        act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        if cache is None:
            out = self.fused_attn(src, attn_mask=src_mask)
        else:
            out, cache = self.fused_attn(src, attn_mask=src_mask,
                                         cache=cache)
        out = self.ffn(out)
        return out if cache is None else (out, cache)


class FusedBiasDropoutResidualLayerNorm(Layer):
    """out = LayerNorm(residual + dropout(x + bias)) in one fused pass
    (reference incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        assert embed_dim > 0
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            shape=[embed_dim], attr=bias_attr,
            default_initializer=I.Constant(0.0), is_bias=True)
        self.ln_scale = self.create_parameter(
            shape=[embed_dim], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            shape=[embed_dim], default_initializer=I.Constant(0.0),
            is_bias=True)

    def forward(self, x, residual):
        from paddle_tpu.incubate.nn.functional import (
            fused_bias_dropout_residual_layer_norm,
        )
        return fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self._dropout_rate, ln_epsilon=self._epsilon,
            training=self.training)


class FusedMultiTransformer(Layer):
    """Fused stack of pre-LN decoder blocks (reference
    incubate/nn/layer/fused_transformer.py FusedMultiTransformer — the
    serving-path stack; per-layer weights live in lists and every block
    runs the fused attention + feedforward kernels)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 num_layers=-1, nranks=1, trans_qkvw=True, ring_id=-1,
                 name=None, **unused):
        super().__init__()
        assert normalize_before, \
            "FusedTransformerEncoderLayer only supports " \
            "normalize_before=True here"
        if num_layers <= 0:
            # the reference's -1 means "infer depth from the per-layer
            # weight lists"; this implementation owns its weights, so a
            # silent 1-layer default would be a porting trap
            raise ValueError(
                "num_layers must be a positive int (the reference's "
                "num_layers=-1 weight-list inference does not apply: "
                "this class creates its own per-layer weights)")
        from paddle_tpu.nn.layer.container import LayerList
        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=True)
            for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, **kw):
        out = src
        if caches is None:
            for layer in self.layers:
                out = layer(out, src_mask=attn_mask)
            return out
        new_caches = []
        for layer, cache in zip(self.layers, caches):
            out, cache = layer(out, src_mask=attn_mask, cache=cache)
            new_caches.append(cache)
        return out, new_caches


class FusedTransformer(Layer):
    """Encoder-decoder built from the fused blocks (reference
    fused_transformer.py FusedTransformer).  The decoder side reuses the
    fused encoder blocks with causal masking — the fused kernels are the
    same; cross-attention runs through the unfused functional path."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, custom_encoder=None,
                 custom_decoder=None):
        super().__init__()
        from paddle_tpu import nn as _nn
        from paddle_tpu.nn.layer.container import LayerList
        # a provided custom_encoder is a single MODULE called once
        # (reference API); the default is our fused per-layer stack
        self._custom_encoder = custom_encoder is not None
        if self._custom_encoder:
            self.encoder = custom_encoder
        else:
            self.encoder = LayerList([
                FusedTransformerEncoderLayer(
                    d_model, nhead, dim_feedforward, dropout_rate=dropout,
                    activation=activation,
                    normalize_before=normalize_before)
                for _ in range(num_encoder_layers)])
        self.decoder = custom_decoder if custom_decoder is not None else \
            _nn.TransformerDecoder(
                _nn.TransformerDecoderLayer(
                    d_model, nhead, dim_feedforward, dropout=dropout,
                    activation=activation,
                    normalize_before=normalize_before),
                num_decoder_layers)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        if self._custom_encoder:
            memory = self.encoder(src, src_mask)
        else:
            memory = src
            for layer in self.encoder:
                memory = layer(memory, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask,
                            memory_mask=memory_mask)


class FusedLinear(Layer):
    """Linear through the fused matmul+bias entry point (reference
    incubate/nn/layer/fused_linear.py FusedLinear — cublasLt epilogue
    fusion there; XLA fuses the bias add into the MXU matmul here)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        from paddle_tpu import nn as _nn
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(
            shape=shape, attr=weight_attr,
            default_initializer=_nn.initializer.XavierNormal())
        self.bias = None if bias_attr is False else self.create_parameter(
            shape=(out_features,), attr=bias_attr, is_bias=True)
        self._transpose_weight = transpose_weight

    def forward(self, x):
        from paddle_tpu.incubate.nn.functional import fused_matmul_bias
        return fused_matmul_bias(x, self.weight, self.bias,
                                 transpose_y=self._transpose_weight)

from paddle_tpu.incubate.nn import layer  # noqa: E402,F401
