"""Paged KV-cache attention — pool-shared decode memory.

Motivated by Ragged Paged Attention (TPU inference kernel,
arXiv:2604.15464, see PAPERS.md) / vLLM's PagedAttention: instead of a
dense per-sequence [max_len] KV buffer, KV lives in a SHARED pool of
fixed-size pages and each sequence owns a small block table of page
ids. Memory scales with TOKENS IN FLIGHT, not batch * max_len, and
sequences grow by appending pages — no re-padding, no fragmentation.

TPU-native rendering (pure XLA, static shapes — the Pallas kernel form
of the paper is a later specialization; the semantics and the memory
model are here):

- pools:        k/v  [num_pages, n_head, page_size, head_dim]
- block table:  [batch, max_pages_per_seq] int32 page ids
- seq lens:     [batch] int32

Decode writes each sequence's new token into page
``table[b, len_b // page]`` at offset ``len_b % page`` (one scatter),
then attends over the sequence's gathered pages with a length mask.
Everything jits; the tape differentiates through the gathers if ever
needed (serving is no_grad).

Three layers of API, outermost first:

- :class:`PagedKVCache` — stateful single-layer cache (Tensor pools +
  embedded allocator), the standalone/demo surface.
- :class:`PageAllocator` — the HOST-side page bookkeeping alone
  (free list, per-slot ownership, leak guards). `paddle_tpu.serving`'s
  engine uses one allocator across all transformer layers while the
  device pools live as per-layer jnp arrays inside its compiled steps.
- pure jnp step functions (:func:`paged_decode_step`,
  :func:`paged_prefill_append`, :func:`paged_attend`) — trace-safe
  building blocks usable inside any jit/to_static program.

Quantized pools: the per-page-scaled int8/fp8 variants of the step
functions live in :mod:`paddle_tpu.quantization.kv_cache` (same page
geometry, pools become ``(codes, scales)`` pairs, ~0.52x bytes/token
vs bf16) — the serving engine selects them via
``EngineConfig(kv_cache_dtype=)``; see docs/quantization.md.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "PageAllocator",
    "PagedKVCache",
    "paged_attend",
    "paged_attention_decode",
    "paged_decode_step",
    "paged_prefill_append",
]


class PageAllocator:
    """Host-side page bookkeeping for a shared pool.

    Page 0 is the reserved GARBAGE page: released slots' block tables
    point at it, so a batch-wide append from an inactive row scatters
    into page 0 and can never corrupt a live sequence.  The allocator
    therefore hands out pages ``1 .. num_pages-1``.

    Invariant (the "no leak" contract): every page is either in the
    free list or owned by exactly one slot.  ``release`` is idempotent
    and guards against double-frees — an eviction mid-decode must
    restore the free list exactly.
    """

    def __init__(self, num_pages, batch, max_pages_per_seq):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self.batch = int(batch)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self._free = list(range(num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._owned = [[] for _ in range(batch)]

    @property
    def num_free_pages(self):
        return len(self._free)

    def owned_pages(self, b):
        return list(self._owned[b])

    def pages_needed(self, n_tokens, page_size):
        return -(-int(n_tokens) // int(page_size))

    def can_allocate(self, b, need):
        """Can slot `b` grow to `need` pages right now?"""
        have = len(self._owned[b])
        if need <= have:
            return True
        if need > self.max_pages_per_seq:
            return False
        return need - have <= len(self._free)

    def allocate(self, b, need):
        """Grow slot `b` to `need` pages; returns [(slot_idx, page_id)]
        newly assigned entries for the caller's block-table update."""
        have = len(self._owned[b])
        if need <= have:
            return []
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"sequence {b} needs {need} pages but max_pages_per_seq "
                f"is {self.max_pages_per_seq}")
        if need - have > len(self._free):
            raise RuntimeError("paged KV cache: out of pages")
        assigned = []
        while len(self._owned[b]) < need:
            pg = self._free.pop()
            self._free_set.discard(pg)
            assigned.append((len(self._owned[b]), pg))
            self._owned[b].append(pg)
        return assigned

    def release(self, b):
        """Return slot `b`'s pages to the pool; returns the freed page
        ids.  Idempotent; raises on a double-free (a page already in the
        free list means the bookkeeping leaked somewhere)."""
        pages = self._owned[b]
        if not pages:
            return []
        dupes = [p for p in pages if p in self._free_set]
        if dupes:
            raise RuntimeError(
                f"paged KV cache: double-free of page(s) {dupes} "
                f"releasing slot {b}")
        self._free.extend(reversed(pages))
        self._free_set.update(pages)
        self._owned[b] = []
        return pages

    def check_invariant(self):
        """All pages accounted for exactly once (free or owned)."""
        owned = [p for o in self._owned for p in o]
        if len(set(owned)) != len(owned):
            raise RuntimeError("paged KV cache: page owned twice")
        if set(owned) & self._free_set:
            raise RuntimeError("paged KV cache: page both owned and free")
        if len(owned) + len(self._free) != self.num_pages - 1:
            raise RuntimeError(
                f"paged KV cache: leak — {len(owned)} owned + "
                f"{len(self._free)} free != {self.num_pages - 1}")
        return True


class PagedKVCache:
    """Shared-pool KV cache with per-sequence block tables.

    num_pages * page_size is the total token capacity shared by ALL
    sequences — size it to tokens-in-flight, not batch * max_len.
    """

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 batch, max_pages_per_seq, dtype=jnp.float32):
        self.page_size = int(page_size)
        self.k_pages = Tensor(jnp.zeros(
            (num_pages, num_heads, page_size, head_dim), dtype))
        self.v_pages = Tensor(jnp.zeros(
            (num_pages, num_heads, page_size, head_dim), dtype))
        self.block_tables = Tensor(jnp.zeros(
            (batch, max_pages_per_seq), jnp.int32))
        self.seq_lens = Tensor(jnp.zeros((batch,), jnp.int32))
        self._alloc = PageAllocator(num_pages, batch, max_pages_per_seq)
        self.max_pages_per_seq = int(max_pages_per_seq)

    @property
    def num_free_pages(self):
        return self._alloc.num_free_pages

    def owned_pages(self, b):
        return self._alloc.owned_pages(b)

    # ---- host-side page allocator (the serving loop's bookkeeping) ----
    def ensure_capacity(self, b, new_len):
        """Allocate pages so sequence `b` can hold `new_len` tokens.

        A slot growing from zero owned pages is a FRESH sequence: its
        device seq_len is reset to 0 so a reused slot can never write
        its first token at a stale offset (the mid-decode-eviction bug:
        released rows used to keep advancing batch-wide)."""
        need = self._alloc.pages_needed(new_len, self.page_size)
        fresh = not self._alloc.owned_pages(b) and need > 0
        assigned = self._alloc.allocate(b, need)
        if assigned:
            tbl = np.array(unwrap(self.block_tables))  # writable host copy
            for slot, pg in assigned:
                tbl[b, slot] = pg
            self.block_tables._set_value(jnp.asarray(tbl))
        if fresh:
            lens = np.asarray(unwrap(self.seq_lens)).copy()
            lens[b] = 0
            self.seq_lens._set_value(jnp.asarray(lens))

    def release(self, b):
        """Finished/evicted sequence: its pages return to the pool; its
        block table resets to the garbage page so further batch-wide
        appends from this row are harmlessly absorbed.  Idempotent, and
        double-frees raise instead of silently growing the pool."""
        self._alloc.release(b)
        tbl = np.array(unwrap(self.block_tables))
        tbl[b, :] = 0
        self.block_tables._set_value(jnp.asarray(tbl))
        lens = np.asarray(unwrap(self.seq_lens)).copy()
        lens[b] = 0
        self.seq_lens._set_value(jnp.asarray(lens))

    def check_invariant(self):
        return self._alloc.check_invariant()

    def _active_mask(self):
        """Rows that own pages are live; released rows must not advance
        their device seq_lens (they'd corrupt the slot on reuse)."""
        return np.array([bool(self._alloc.owned_pages(b))
                         for b in range(len(self._alloc._owned))])

    def append_and_attend(self, q, k_new, v_new, scale=None, active=None):
        """One decode step for every sequence: write each row's new
        token at its own position, return attention over its pages.

        q/k_new/v_new: [batch, n_head, 1, head_dim].  `active` ([batch]
        bool, default: rows owning pages) masks which rows' seq_lens
        advance — inactive rows scatter into the garbage page and stay
        put, so an evicted slot is bit-exactly fresh when reused.
        """
        if active is None:
            active = self._active_mask()
        active = jnp.asarray(np.asarray(active), jnp.bool_)
        out, kp, vp, lens = apply(
            lambda qv, kv, vv, kpg, vpg, tbl, ln, act: _paged_step(
                qv, kv, vv, kpg, vpg, tbl, ln, act, self.page_size, scale),
            q, k_new, v_new, self.k_pages, self.v_pages,
            self.block_tables, self.seq_lens, active)
        self.k_pages._set_value(kp._value)
        self.v_pages._set_value(vp._value)
        self.seq_lens._set_value(lens._value)
        return out

    def append_prefill(self, k_new, v_new, lens):
        """Batched multi-sequence prompt write: scatter each row's first
        ``lens[b]`` tokens into its pages (token t of row b lands in
        page ``table[b, t // page]`` at offset ``t % page``).  Callers
        must have ``ensure_capacity(b, lens[b])``-ed every row first.

        k_new/v_new: [batch, n_head, S, head_dim]; lens: [batch] int.
        Positions >= lens[b] (padding) are directed to the garbage page.

        Rows NOT being prefilled must pass ``lens[b] == 0``: they
        scatter nothing and their existing device seq_len is preserved
        (lens are MERGED, not overwritten), so a partial-batch prefill
        cannot reset or corrupt rows that are mid-decode.
        """
        lens = jnp.asarray(np.asarray(lens), jnp.int32)
        kp, vp = apply(
            lambda kv, vv, kpg, vpg, tbl, ln: paged_prefill_append(
                kv, vv, kpg, vpg, tbl, ln, self.page_size),
            k_new, v_new, self.k_pages, self.v_pages,
            self.block_tables, lens)
        self.k_pages._set_value(kp._value)
        self.v_pages._set_value(vp._value)
        merged = jnp.where(lens > 0, lens,
                           unwrap(self.seq_lens).astype(jnp.int32))
        self.seq_lens._set_value(merged)


def paged_attend(q, k_pages, v_pages, tables, lens, page_size, scale=None):
    """Shared attention core: [b, h, 1, d] queries over each row's
    gathered pages, masked at `lens` — used by the stateful step, the
    functional read-only decode, and serving's compiled decode step."""
    b, h, one, d = q.shape
    sc = scale if scale is not None else 1.0 / float(d) ** 0.5
    k_seq = k_pages[tables]                               # [b, P, h, p, d]
    v_seq = v_pages[tables]
    P = tables.shape[1]
    k_seq = jnp.moveaxis(k_seq, 2, 1).reshape(b, h, P * page_size, d)
    v_seq = jnp.moveaxis(v_seq, 2, 1).reshape(b, h, P * page_size, d)
    pos = jnp.arange(P * page_size)
    mask = pos[None, None, None, :] < lens[:, None, None, None]
    # narrow (bf16/fp16/quantized-dequant) pools: accumulate both
    # contractions WIDE and round once (numlint NL101) — the value
    # matmul reduces over the ENTIRE cached history, the deepest sum in
    # the serving path.  f32 pools take the identical pre-fix jaxpr.
    narrow = q.dtype in (jnp.bfloat16, jnp.float16)
    pet = {"preferred_element_type": jnp.float32} if narrow else {}
    s = jnp.matmul(q * sc, jnp.swapaxes(k_seq, -1, -2),
                   **pet)                                 # [b, h, 1, Pp]
    s = jnp.where(mask, s.astype(jnp.float32),
                  jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.matmul(p, v_seq, **pet).astype(q.dtype)    # [b, h, 1, d]


_attend_pages = paged_attend  # back-compat alias (pre-serving name)


def paged_decode_step(q, k_new, v_new, k_pages, v_pages, tables, lens,
                      page_size, scale=None):
    """Pure decode step WITHOUT length bookkeeping: write each row's new
    token at position ``lens[b]``, attend over ``lens[b]+1`` tokens.
    Returns (out, k_pages, v_pages); the caller owns the lens update —
    a multi-layer engine calls this once per layer with the SAME lens
    and advances lens once per step.
    """
    lens = lens.astype(jnp.int32)
    page_idx = lens // page_size
    offs = lens % page_size
    page_ids = jnp.take_along_axis(tables, page_idx[:, None],
                                   axis=1)[:, 0]          # [b]
    # scatter each row's token into its page/offset — the pool-dtype
    # narrowing is EXPLICIT (numlint-visible cast, and jax deprecates
    # the implicit f32->bf16 scatter cast) rather than hidden in the
    # scatter
    kt = jnp.swapaxes(k_new, 1, 2)[:, 0].astype(k_pages.dtype)
    vt = jnp.swapaxes(v_new, 1, 2)[:, 0].astype(v_pages.dtype)
    k_pages = k_pages.at[page_ids, :, offs].set(kt)
    v_pages = v_pages.at[page_ids, :, offs].set(vt)
    out = paged_attend(q, k_pages, v_pages, tables, lens + 1,
                       page_size, scale)
    return out, k_pages, v_pages


def _paged_step(q, k_new, v_new, k_pages, v_pages, tables, lens, active,
                page_size, scale):
    out, k_pages, v_pages = paged_decode_step(
        q, k_new, v_new, k_pages, v_pages, tables, lens, page_size, scale)
    new_lens = lens.astype(jnp.int32) + active.astype(jnp.int32)
    return out, k_pages, v_pages, new_lens


def paged_prefill_append(k_new, v_new, k_pages, v_pages, tables, lens,
                         page_size):
    """Batched multi-sequence prompt scatter (pure): token t of row b
    lands in page ``tables[b, t // page_size]`` at offset
    ``t % page_size``; positions >= lens[b] go to the garbage page 0.

    k_new/v_new: [b, h, S, d].  Returns (k_pages, v_pages).
    """
    b, h, S, d = k_new.shape
    t = jnp.arange(S, dtype=jnp.int32)
    page_idx = t // page_size                              # [S]
    offs = t % page_size                                   # [S]
    # clamp in case S spans more pages than the table width — the
    # valid-mask below routes those to garbage anyway
    page_idx = jnp.minimum(page_idx, tables.shape[1] - 1)
    page_ids = tables[:, page_idx]                         # [b, S]
    valid = t[None, :] < lens[:, None].astype(jnp.int32)
    page_ids = jnp.where(valid, page_ids, 0)
    flat_pages = page_ids.reshape(-1)                      # [b*S]
    flat_offs = jnp.tile(offs, b)
    # explicit pool-dtype narrowing (see paged_decode_step)
    kt = jnp.swapaxes(k_new, 1, 2).reshape(b * S, h, d) \
        .astype(k_pages.dtype)                             # [b*S, h, d]
    vt = jnp.swapaxes(v_new, 1, 2).reshape(b * S, h, d) \
        .astype(v_pages.dtype)
    k_pages = k_pages.at[flat_pages, :, flat_offs].set(kt)
    v_pages = v_pages.at[flat_pages, :, flat_offs].set(vt)
    return k_pages, v_pages


def paged_attention_decode(q, k_pages, v_pages, block_tables, seq_lens,
                           page_size, scale=None):
    """Functional read-only form: attention of [b, h, 1, d] queries over
    already-written pages (positions < seq_lens)."""
    return apply(
        lambda qv, kpg, vpg, tbl, ln: paged_attend(
            qv, kpg, vpg, tbl, ln, page_size, scale),
        q, k_pages, v_pages, block_tables, seq_lens)
