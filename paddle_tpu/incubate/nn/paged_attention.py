"""Paged KV-cache attention — pool-shared decode memory.

Motivated by Ragged Paged Attention (TPU inference kernel,
arXiv:2604.15464, see PAPERS.md) / vLLM's PagedAttention: instead of a
dense per-sequence [max_len] KV buffer, KV lives in a SHARED pool of
fixed-size pages and each sequence owns a small block table of page
ids. Memory scales with TOKENS IN FLIGHT, not batch * max_len, and
sequences grow by appending pages — no re-padding, no fragmentation.

TPU-native rendering (pure XLA, static shapes — the Pallas kernel form
of the paper is a later specialization; the semantics and the memory
model are here):

- pools:        k/v  [num_pages, n_head, page_size, head_dim]
- block table:  [batch, max_pages_per_seq] int32 page ids
- seq lens:     [batch] int32

Decode writes each sequence's new token into page
``table[b, len_b // page]`` at offset ``len_b % page`` (one scatter),
then attends over the sequence's gathered pages with a length mask.
Everything jits; the tape differentiates through the gathers if ever
needed (serving is no_grad).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor

__all__ = ["PagedKVCache", "paged_attention_decode"]


class PagedKVCache:
    """Shared-pool KV cache with per-sequence block tables.

    num_pages * page_size is the total token capacity shared by ALL
    sequences — size it to tokens-in-flight, not batch * max_len.
    """

    def __init__(self, num_pages, page_size, num_heads, head_dim,
                 batch, max_pages_per_seq, dtype=jnp.float32):
        self.page_size = int(page_size)
        self.k_pages = Tensor(jnp.zeros(
            (num_pages, num_heads, page_size, head_dim), dtype))
        self.v_pages = Tensor(jnp.zeros(
            (num_pages, num_heads, page_size, head_dim), dtype))
        self.block_tables = Tensor(jnp.zeros(
            (batch, max_pages_per_seq), jnp.int32))
        self.seq_lens = Tensor(jnp.zeros((batch,), jnp.int32))
        # page 0 is the reserved GARBAGE page: released rows' block
        # tables point at it, so a batch-wide append from a finished row
        # scatters into page 0 and can never corrupt a live sequence
        self._free = list(range(num_pages - 1, 0, -1))
        self._owned = [[] for _ in range(batch)]
        self.max_pages_per_seq = int(max_pages_per_seq)

    # ---- host-side page allocator (the serving loop's bookkeeping) ----
    def ensure_capacity(self, b, new_len):
        """Allocate pages so sequence `b` can hold `new_len` tokens."""
        need = -(-int(new_len) // self.page_size)
        if len(self._owned[b]) >= need:
            return                      # common case: no transfer at all
        if need > self.max_pages_per_seq:
            raise ValueError(
                f"sequence {b} needs {need} pages but max_pages_per_seq "
                f"is {self.max_pages_per_seq}")
        if need - len(self._owned[b]) > len(self._free):
            raise RuntimeError("paged KV cache: out of pages")
        tbl = np.array(unwrap(self.block_tables))  # writable host copy
        while len(self._owned[b]) < need:
            pg = self._free.pop()
            slot = len(self._owned[b])
            self._owned[b].append(pg)
            tbl[b, slot] = pg
        self.block_tables._set_value(jnp.asarray(tbl))

    def release(self, b):
        """Finished sequence: its pages return to the pool; its block
        table resets to the garbage page so further batch-wide appends
        from this row are harmlessly absorbed."""
        self._free.extend(reversed(self._owned[b]))
        self._owned[b] = []
        tbl = np.array(unwrap(self.block_tables))
        tbl[b, :] = 0
        self.block_tables._set_value(jnp.asarray(tbl))
        lens = np.asarray(unwrap(self.seq_lens)).copy()
        lens[b] = 0
        self.seq_lens._set_value(jnp.asarray(lens))

    def append_and_attend(self, q, k_new, v_new, scale=None):
        """One decode step for every sequence: write each row's new
        token at its own position, return attention over its pages.

        q/k_new/v_new: [batch, n_head, 1, head_dim].
        """
        out, kp, vp, lens = apply(
            lambda qv, kv, vv, kpg, vpg, tbl, ln: _paged_step(
                qv, kv, vv, kpg, vpg, tbl, ln, self.page_size, scale),
            q, k_new, v_new, self.k_pages, self.v_pages,
            self.block_tables, self.seq_lens)
        self.k_pages._set_value(kp._value)
        self.v_pages._set_value(vp._value)
        self.seq_lens._set_value(lens._value)
        return out


def _attend_pages(q, k_pages, v_pages, tables, lens, page_size, scale):
    """Shared attention core: [b, h, 1, d] queries over each row's
    gathered pages, masked at `lens` — used by both the stateful step
    and the functional read-only decode."""
    b, h, one, d = q.shape
    sc = scale if scale is not None else 1.0 / float(d) ** 0.5
    k_seq = k_pages[tables]                               # [b, P, h, p, d]
    v_seq = v_pages[tables]
    P = tables.shape[1]
    k_seq = jnp.moveaxis(k_seq, 2, 1).reshape(b, h, P * page_size, d)
    v_seq = jnp.moveaxis(v_seq, 2, 1).reshape(b, h, P * page_size, d)
    pos = jnp.arange(P * page_size)
    mask = pos[None, None, None, :] < lens[:, None, None, None]
    s = (q * sc) @ jnp.swapaxes(k_seq, -1, -2)            # [b, h, 1, Pp]
    s = jnp.where(mask, s.astype(jnp.float32),
                  jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return p @ v_seq                                      # [b, h, 1, d]


def _paged_step(q, k_new, v_new, k_pages, v_pages, tables, lens,
                page_size, scale):
    lens = lens.astype(jnp.int32)
    page_idx = lens // page_size
    offs = lens % page_size
    page_ids = jnp.take_along_axis(tables, page_idx[:, None],
                                   axis=1)[:, 0]          # [b]
    # scatter each row's token into its page/offset
    kt = jnp.swapaxes(k_new, 1, 2)[:, 0]                  # [b, h, d]
    vt = jnp.swapaxes(v_new, 1, 2)[:, 0]
    k_pages = k_pages.at[page_ids, :, offs].set(kt)
    v_pages = v_pages.at[page_ids, :, offs].set(vt)
    new_lens = lens + 1
    out = _attend_pages(q, k_pages, v_pages, tables, new_lens,
                        page_size, scale)
    return out, k_pages, v_pages, new_lens


def paged_attention_decode(q, k_pages, v_pages, block_tables, seq_lens,
                           page_size, scale=None):
    """Functional read-only form: attention of [b, h, 1, d] queries over
    already-written pages (positions < seq_lens)."""
    return apply(
        lambda qv, kpg, vpg, tbl, ln: _attend_pages(
            qv, kpg, vpg, tbl, ln, page_size, scale),
        q, k_pages, v_pages, block_tables, seq_lens)
