"""paddle.incubate parity namespace (reference: python/paddle/incubate).

Hosts the fused transformer ops/layers; the rest of the reference's
incubate surface either graduated into core namespaces here (flash
attention lives in ops/pallas + nn.functional.scaled_dot_product_attention)
or is GPU-runtime-specific with no TPU analogue.
"""
from paddle_tpu.incubate import nn  # noqa: F401
