"""paddle.incubate parity namespace (reference: python/paddle/incubate).

Fused transformer ops/layers, MoE, LookAhead/ModelAverage, fused
softmax-mask ops, graph sampling ops and segment reductions — the same
public __all__ as the reference's incubate/__init__.py:42.  Pieces of the
reference incubate surface that graduated into core namespaces here are
re-exported from them (flash attention lives in ops/pallas +
nn.functional.scaled_dot_product_attention).
"""
from paddle_tpu.incubate import multiprocessing  # noqa: F401
from paddle_tpu.incubate import asp  # noqa: F401
from paddle_tpu.incubate import autotune  # noqa: F401
from paddle_tpu.incubate import autograd  # noqa: F401
from paddle_tpu.incubate import distributed  # noqa: F401
from paddle_tpu.incubate import nn  # noqa: F401
from paddle_tpu.incubate import operators  # noqa: F401
from paddle_tpu.incubate.operators import (  # noqa: F401
    graph_khop_sampler,
    graph_reindex,
    graph_sample_neighbors,
    graph_send_recv,
    softmax_mask_fuse,
    softmax_mask_fuse_upper_triangle,
)
from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage  # noqa: F401
from paddle_tpu.geometric import (  # noqa: F401
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)


def identity_loss(x, reduction="none"):
    """Mark a loss for IPU-style pipelining in the reference
    (incubate/__init__.py identity_loss); numerically it reduces or passes
    through the input."""
    import paddle_tpu
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    if reduction in (2, "none"):
        return x
    raise ValueError("reduction must be sum|mean|none")


__all__ = [
    "LookAhead",
    "ModelAverage",
    "softmax_mask_fuse_upper_triangle",
    "softmax_mask_fuse",
    "graph_send_recv",
    "graph_khop_sampler",
    "graph_sample_neighbors",
    "graph_reindex",
    "segment_sum",
    "segment_mean",
    "segment_max",
    "segment_min",
    "identity_loss",
]


def set_config(config=None):
    """paddle.incubate.set_config — the autotune configuration entry
    (reference incubate/__init__.py re-exports autotune.set_config)."""
    from paddle_tpu.incubate.autotune import set_config as _set
    return _set(config)
