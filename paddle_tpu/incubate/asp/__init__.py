"""Automatic SParsity (n:m structured pruning).

Reference parity: python/paddle/incubate/asp/__init__.py exporting
fluid/contrib/sparsity/{utils,asp}.py (get_mask_1d :186,
get_mask_2d_best :433, create_mask :487, check_sparsity :556,
prune_model / decorate in asp.py).

The reference targets NVIDIA sparse tensor cores; TPUs have no 2:4
hardware path, so here ASP is the hardware-agnostic part of the story:
mask generation, pruning, and the optimizer decoration that keeps pruned
weights at zero through training (masks re-applied after each step as a
multiply the XLA compiler fuses into the update).
"""
from __future__ import annotations

from enum import Enum
from itertools import combinations, product

import numpy as np

__all__ = [
    "MaskAlgo", "CheckMethod", "calculate_density", "get_mask_1d",
    "get_mask_2d_greedy", "get_mask_2d_best", "create_mask",
    "check_mask_1d", "check_mask_2d", "check_sparsity", "decorate",
    "prune_model", "set_excluded_layers", "reset_excluded_layers",
]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo):
        assert isinstance(mask_algo, MaskAlgo)
        return CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D \
            else CheckMethod.CHECK_2D


def calculate_density(x):
    """Fraction of non-zero entries (reference utils.py:93)."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


def _pad_to(mat, m):
    h, w = mat.shape
    ph = (m - h % m) % m
    pw = (m - w % m) % m
    if ph or pw:
        mat = np.pad(mat, ((0, ph), (0, pw)))
    return mat, h, w


def get_mask_1d(mat, n, m):
    """Keep the n largest-|.|(of every m consecutive values along rows)."""
    mat = np.asarray(mat)
    padded, h, w = _pad_to(mat, m)
    blocks = np.abs(padded.reshape(padded.shape[0], -1, m))
    order = np.argsort(-blocks, axis=-1)
    mask = np.zeros_like(blocks)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    return mask.reshape(padded.shape)[:h, :w]


def get_mask_2d_greedy(mat, n, m):
    """m x m blocks with at most n survivors per row AND column, chosen
    greedily by magnitude (reference utils.py get_mask_2d_greedy)."""
    mat = np.asarray(mat)
    padded, h, w = _pad_to(mat, m)
    mask = np.zeros_like(padded)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = np.abs(padded[bi:bi + m, bj:bj + m])
            order = np.argsort(-block.ravel())
            rows = np.zeros(m, np.int64)
            cols = np.zeros(m, np.int64)
            taken = np.zeros((m, m), bool)
            for flat in order:
                r, c = divmod(int(flat), m)
                if rows[r] < n and cols[c] < n:
                    taken[r, c] = True
                    rows[r] += 1
                    cols[c] += 1
            # pure greedy can strand capacity (a deficient row's only
            # open columns are ones it already uses); complete to
            # exactly n per row AND col with one-swap augmenting moves
            while (rows < n).any():
                r = int(np.argmin(rows))
                deficit = [c for c in range(m) if cols[c] < n]
                free = [c for c in deficit if not taken[r, c]]
                if free:
                    c = max(free, key=lambda cc: block[r, cc])
                    taken[r, c] = True
                    rows[r] += 1
                    cols[c] += 1
                    continue
                c = deficit[0]
                for c2 in range(m):
                    if cols[c2] >= n and not taken[r, c2]:
                        donors = [rr for rr in range(m)
                                  if taken[rr, c2] and not taken[rr, c]]
                        if donors:
                            # a donor always exists: col c2 has n users,
                            # deficit col c has < n, so some c2-user is
                            # free to move to c
                            rr = max(donors, key=lambda x: block[x, c])
                            taken[rr, c2] = False
                            taken[rr, c] = True
                            cols[c2] -= 1
                            cols[c] += 1
                            taken[r, c2] = True
                            cols[c2] += 1
                            rows[r] += 1
                            break
            mask[bi:bi + m, bj:bj + m] = taken
    return mask[:h, :w]


def _best_patterns(n, m):
    """All m x m 0/1 patterns with exactly n per row and per column."""
    key = (n, m)
    if key not in _best_patterns._cache:
        row_choices = list(combinations(range(m), n))
        pats = []
        # product, not permutations: rows may legally pick the SAME
        # column set (e.g. the 2:4 block-diagonal pattern)
        for rows in product(row_choices, repeat=m) if m <= 4 else ():
            p = np.zeros((m, m))
            for r, cols in enumerate(rows):
                p[r, list(cols)] = 1.0
            if (p.sum(0) == n).all():
                pats.append(p)
        _best_patterns._cache[key] = pats
    return _best_patterns._cache[key]


_best_patterns._cache = {}


def get_mask_2d_best(mat, n, m):
    """Exhaustive best n:m 2-D pattern per m x m block (m<=4; falls back
    to greedy otherwise) — reference utils.py:433."""
    pats = _best_patterns(n, m)
    if not pats:
        return get_mask_2d_greedy(mat, n, m)
    mat = np.asarray(mat)
    padded, h, w = _pad_to(mat, m)
    mask = np.zeros_like(padded)
    stack = np.stack(pats)  # [P, m, m]
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            block = np.abs(padded[bi:bi + m, bj:bj + m])
            scores = (stack * block).sum(axis=(1, 2))
            mask[bi:bi + m, bj:bj + m] = stack[int(scores.argmax())]
    return mask[:h, :w]


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    """n:m mask with the same shape as `tensor`; >2-D tensors are pruned
    on their 2-D [prod(leading), last] view (reference utils.py:487)."""
    arr = np.asarray(tensor, dtype=np.float32)
    shape = arr.shape
    mat = arr.reshape(-1, shape[-1]) if arr.ndim != 2 else arr
    fn = globals()[func_name.value if isinstance(func_name, MaskAlgo)
                   else str(func_name)]
    return fn(mat, n, m).reshape(shape)


def check_mask_1d(mat, n, m):
    mat = np.asarray(mat)
    padded, _, _ = _pad_to(mat, m)
    blocks = padded.reshape(padded.shape[0], -1, m)
    return bool((np.count_nonzero(blocks, axis=-1) <= n).all())


def check_mask_2d(mat, n, m):
    mat = np.asarray(mat)
    padded, _, _ = _pad_to(mat, m)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            b = padded[bi:bi + m, bj:bj + m]
            if (np.count_nonzero(b, axis=0) > n).any() or \
                    (np.count_nonzero(b, axis=1) > n).any():
                return False
    return True


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4):
    arr = np.asarray(tensor)
    mat = arr.reshape(-1, arr.shape[-1]) if arr.ndim != 2 else arr
    fn = globals()[func_name.value if isinstance(func_name, CheckMethod)
                   else str(func_name)]
    return fn(mat, n, m)


# ---------------------------------------------------------------- ASP state
class ASPHelper:
    """Per-process mask registry (reference asp.py ASPHelper)."""

    _masks = {}          # id(param) -> (param, mask ndarray)
    _excluded = set()    # layer-name prefixes
    _extra_supported = {}  # add_supported_layer registrations

    @classmethod
    def _registration_for(cls, name):
        for key, fn in cls._extra_supported.items():
            if key in name.lower():
                return key, fn
        return None, None

    @classmethod
    def is_supported(cls, name, param):
        if any(name.startswith(e) for e in cls._excluded):
            return False
        shape = tuple(param._value.shape)
        if len(shape) < 2:
            return False
        if cls._registration_for(name)[0] is not None:
            return True  # add_supported_layer registration wins
        return shape[-1] % 4 == 0

    @classmethod
    def prune(cls, model, n, m, mask_algo, with_mask):
        import jax.numpy as jnp
        pruned = {}
        for name, p in model.named_parameters():
            if not name.endswith("weight") or not cls.is_supported(name, p):
                continue
            _, custom = cls._registration_for(name)
            if custom is not None:
                # registered pruning_func(weight, m, n, algo_name, name)
                # -> (pruned_weight, mask), the reference's contract
                w, mask = custom(np.asarray(p._value), m, n,
                                 getattr(mask_algo, "value", mask_algo),
                                 name)
                p._set_value(jnp.asarray(w, p._value.dtype))
            else:
                mask = create_mask(np.asarray(p._value), mask_algo, n, m)
                p._set_value(p._value * jnp.asarray(mask, p._value.dtype))
            if with_mask:
                cls._masks[id(p)] = (p, mask)
            pruned[name] = mask
        return pruned

    @classmethod
    def apply_masks(cls):
        import jax.numpy as jnp
        for p, mask in cls._masks.values():
            p._set_value(p._value * jnp.asarray(mask, p._value.dtype))


def add_supported_layer(layer, pruning_func=None):
    """Register an extra layer type (or parameter-name prefix) as
    prunable (reference asp/supported_layer_list.py add_supported_layer).
    With `pruning_func`, it is called as pruning_func(weight_ndarray, m,
    n, mask_algo, param_name) -> (pruned_weight, mask) during
    prune_model."""
    name = (layer if isinstance(layer, str)
            else getattr(layer, "__name__", str(layer))).lower()
    ASPHelper._extra_supported[name] = pruning_func
    return name


def set_excluded_layers(param_names, main_program=None):
    ASPHelper._excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Prune every supported weight of `model` to n:m sparsity and (with
    with_mask) register the masks so a decorated optimizer keeps them
    (reference asp.py prune_model)."""
    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    return ASPHelper.prune(model, n, m, algo, with_mask)


class OptimizerWithSparsityGuarantee:
    """Re-applies the registered masks after every step so pruned weights
    stay exactly zero through training (reference asp.py decorate)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        ASPHelper.apply_masks()

    def minimize(self, loss, *args, **kwargs):
        out = self._optimizer.minimize(loss, *args, **kwargs)
        ASPHelper.apply_masks()
        return out


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)
