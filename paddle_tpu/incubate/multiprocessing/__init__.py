"""paddle.incubate.multiprocessing parity (reference:
incubate/multiprocessing/reductions.py): make Tensors picklable across
process boundaries for DataLoader workers.

The reference registers CUDA-IPC reductions; device memory here is not
process-shareable (the TPU claim is exclusive), so tensors reduce
through host numpy buffers — correct everywhere, zero-copy nowhere.
"""
from __future__ import annotations

import copyreg

__all__ = ["init_reductions"]

_installed = [False]


def _rebuild_tensor(array, stop_gradient):
    import paddle_tpu
    t = paddle_tpu.to_tensor(array)
    t.stop_gradient = stop_gradient
    return t


def _reduce_tensor(t):
    return _rebuild_tensor, (t.numpy(), t.stop_gradient)


def init_reductions():
    """Register pickle reductions for Tensor (idempotent)."""
    if _installed[0]:
        return
    from paddle_tpu.core.tensor import Parameter, Tensor
    copyreg.pickle(Tensor, _reduce_tensor)
    copyreg.pickle(Parameter, _reduce_tensor)
    _installed[0] = True
