"""paddle.incubate.autograd parity (reference:
python/paddle/incubate/autograd/__init__.py)."""
from paddle_tpu.autograd.functional import (  # noqa: F401
    Hessian,
    Jacobian,
    jvp,
    vjp,
)

__all__ = ["vjp", "jvp", "Jacobian", "Hessian"]
