"""paddle.incubate.autograd parity (reference:
python/paddle/incubate/autograd/__init__.py: vjp, jvp, Jacobian,
Hessian, enable_prim/disable_prim, forward_grad, grad).

The reference's primitive machinery (Registry/REGISTER_JVP/orig2prim/
prim2orig transform passes) hand-builds a primitive-level autodiff over
ProgramDesc. JAX *is* that system here — every op already lowers to
differentiable primitives — so enable_prim/disable_prim are honest
flags (primitive mode is always on) and forward_grad/grad run jax's
native forward/reverse transforms through the same functional surface.
"""
from __future__ import annotations

from paddle_tpu.autograd.functional import (  # noqa: F401
    Hessian,
    Jacobian,
    jvp,
    vjp,
)

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled", "forward_grad", "grad"]

_prim_flag = [True]


def enable_prim():
    _prim_flag[0] = True


def disable_prim():
    # accepted for API parity; ops always execute as jax primitives
    _prim_flag[0] = False


def prim_enabled():
    return _prim_flag[0]


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode gradients (reference primapi.py forward_grad).

    The reference form takes static-graph VARS and rewrites the program;
    that form has no analogue over an already-executed eager graph (the
    tape stores reverse pullbacks). The working contract here is the
    functional one: pass the FUNCTION as `outputs` and its inputs/seed
    tangents, and this is exactly one jax jvp —
    ``forward_grad(fn, xs, v) == jvp(fn, xs, v)[1]``.
    """
    if callable(outputs):
        import jax.numpy as jnp

        from paddle_tpu.core.tensor import Tensor

        ins = [inputs] if isinstance(inputs, Tensor) else list(inputs)
        if grad_inputs is None:
            tangents = tuple(Tensor(jnp.ones_like(t._value)) for t in ins)
        else:
            tangents = tuple(
                [grad_inputs] if isinstance(grad_inputs, Tensor)
                else list(grad_inputs))
        _, tangent_out = jvp(outputs, tuple(ins), tangents)
        return tangent_out
    raise NotImplementedError(
        "forward_grad over captured eager outputs is not representable "
        "(the tape records reverse pullbacks); pass the function itself: "
        "forward_grad(fn, inputs, seed_tangents)")


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode gradients (reference primapi.py grad): same
    contract as paddle.grad, provided here at the incubate path."""
    from paddle_tpu.autograd import grad as _eager_grad
    return _eager_grad(outputs, inputs, grad_outputs,
                       retain_graph=True, allow_unused=True)
