"""Fused ResNet unit (reference: python/paddle/incubate/operators/
resnet_unit.py:24 `resnet_unit`, :150 `ResNetUnit`).

The reference backs this with a cuDNN-fused conv+BN+add+relu CUDA kernel.
On TPU the same fusion falls out of XLA: the convolution lowers onto the
MXU and the BN affine, residual add and relu fuse into its epilogue —
one kernel, no materialised intermediates, which is exactly the
contract the reference op exists to provide.  We therefore express the
unit as a jnp composition over the existing functional conv/batch_norm
and let the compiler do what cuDNN's hand-fused kernel does.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu.nn.functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer.layers import Layer

__all__ = ["resnet_unit", "ResNetUnit"]


def _bn_vec(p):
    """Reference BN params are [1,C,1,1]/[1,1,1,C]; functional batch_norm
    wants (C,)."""
    if p is None:
        return None
    return p.reshape([-1]) if p.ndim > 1 else p


def resnet_unit(x, filter_x, scale_x, bias_x, mean_x, var_x,
                z, filter_z, scale_z, bias_z, mean_z, var_z,
                stride=1, stride_z=1, padding=0, dilation=1, groups=1,
                momentum=0.9, eps=1e-5, data_format="NHWC",
                fuse_add=False, has_shortcut=False,
                use_global_stats=False, is_test=False, act="relu"):
    """conv(x)+BN [+ conv(z)+BN or +z] -> act, fused by XLA on TPU."""
    out = F.conv2d(x, filter_x, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    out = F.batch_norm(out, _bn_vec(mean_x), _bn_vec(var_x),
                       weight=_bn_vec(scale_x), bias=_bn_vec(bias_x),
                       training=not is_test, momentum=momentum,
                       epsilon=eps, data_format=data_format,
                       use_global_stats=use_global_stats)
    if has_shortcut:
        sc = F.conv2d(z, filter_z, stride=stride_z, padding=padding,
                      dilation=dilation, groups=groups,
                      data_format=data_format)
        sc = F.batch_norm(sc, _bn_vec(mean_z), _bn_vec(var_z),
                          weight=_bn_vec(scale_z), bias=_bn_vec(bias_z),
                          training=not is_test, momentum=momentum,
                          epsilon=eps, data_format=data_format,
                          use_global_stats=use_global_stats)
        out = out + sc
    elif fuse_add:
        out = out + z
    if act == "relu":
        out = F.relu(out)
    elif act not in (None, "identity", ""):
        out = getattr(F, act)(out)
    return out


class ResNetUnit(Layer):
    """Layer wrapper matching reference ResNetUnit (resnet_unit.py:150):
    holds the conv filter + BN affine/moving stats for the main branch
    and, when `has_shortcut`, a second filter+BN set for the shortcut.
    """

    def __init__(self, num_channels_x, num_filters, filter_size,
                 stride=1, momentum=0.9, eps=1e-5, data_format="NHWC",
                 act="relu", fuse_add=False, has_shortcut=False,
                 use_global_stats=False, is_test=False,
                 filter_x_attr=None, scale_x_attr=None, bias_x_attr=None,
                 moving_mean_x_name=None, moving_var_x_name=None,
                 num_channels_z=None, stride_z=1, filter_z_attr=None,
                 scale_z_attr=None, bias_z_attr=None,
                 moving_mean_z_name=None, moving_var_z_name=None):
        super().__init__()
        self._stride = stride
        self._stride_z = stride_z
        self._dilation = 1
        self._kernel_size = (filter_size, filter_size)
        self._padding = (filter_size - 1) // 2
        self._groups = 1
        self._momentum = momentum
        self._eps = eps
        self._data_format = data_format
        self._act = act
        self._fuse_add = fuse_add
        self._has_shortcut = has_shortcut
        self._use_global_stats = use_global_stats
        self._is_test = is_test

        def he_init(cin):
            std = (2.0 / (filter_size * filter_size * cin)) ** 0.5
            return I.Normal(0.0, std)

        def make_branch(prefix, cin, attr_f, attr_s, attr_b):
            # filters stored OIHW like nn.Conv2D regardless of data_format
            f = self.create_parameter(
                [num_filters, cin, filter_size, filter_size],
                attr=attr_f, default_initializer=he_init(cin))
            s = self.create_parameter([num_filters], attr=attr_s,
                                      dtype="float32",
                                      default_initializer=I.Constant(1.0))
            b = self.create_parameter([num_filters], attr=attr_b,
                                      dtype="float32", is_bias=True)
            m = self.create_parameter([num_filters], dtype="float32",
                                      default_initializer=I.Constant(0.0))
            v = self.create_parameter([num_filters], dtype="float32",
                                      default_initializer=I.Constant(1.0))
            m.stop_gradient = True
            m.trainable = False
            v.stop_gradient = True
            v.trainable = False
            setattr(self, "filter_" + prefix, f)
            setattr(self, "scale_" + prefix, s)
            setattr(self, "bias_" + prefix, b)
            setattr(self, "mean_" + prefix, m)
            setattr(self, "var_" + prefix, v)

        make_branch("x", num_channels_x, filter_x_attr, scale_x_attr,
                    bias_x_attr)
        if has_shortcut:
            make_branch("z", num_channels_z or num_channels_x,
                        filter_z_attr, scale_z_attr, bias_z_attr)
        else:
            self.filter_z = self.scale_z = self.bias_z = None
            self.mean_z = self.var_z = None

    def forward(self, x, z=None):
        if self._fuse_add and z is None:
            raise ValueError("fuse_add=True requires z")
        return resnet_unit(
            x, self.filter_x, self.scale_x, self.bias_x, self.mean_x,
            self.var_x, z, self.filter_z, self.scale_z, self.bias_z,
            self.mean_z, self.var_z, self._stride, self._stride_z,
            self._padding, self._dilation, self._groups, self._momentum,
            self._eps, self._data_format, self._fuse_add,
            self._has_shortcut, self._use_global_stats, self._is_test,
            self._act)
