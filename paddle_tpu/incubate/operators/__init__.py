"""paddle.incubate.operators parity (reference:
python/paddle/incubate/operators/).

The reference implements these as hand-written CUDA kernels; here each is
a small jnp composition that XLA fuses into one kernel on TPU — the
"fused" contract (no materialised intermediate) holds by construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply

from paddle_tpu.incubate.operators.resnet_unit import (  # noqa: F401
    ResNetUnit,
    resnet_unit,
)

__all__ = [
    "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle",
    "graph_send_recv",
    "graph_khop_sampler",
    "graph_sample_neighbors",
    "graph_reindex",
    "resnet_unit",
    "ResNetUnit",
]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) fused (reference operators/softmax_mask_fuse.py;
    mask broadcasts over heads, holds -10000 at masked positions)."""
    return apply(lambda xv, mv: jax.nn.softmax(
        xv.astype(jnp.float32) + mv.astype(jnp.float32),
        axis=-1).astype(xv.dtype), x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """softmax with the causal (upper-triangle masked) pattern fused
    (reference operators/softmax_mask_fuse_upper_triangle.py): scores at
    column > row are masked out. x: [b, h, sq, sk]."""

    def fn(xv):
        sq, sk = xv.shape[-2], xv.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row, xv.astype(jnp.float32), -1e9)
        return jax.nn.softmax(s, axis=-1).astype(xv.dtype)

    return apply(fn, x)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Deprecated alias of geometric.send_u_recv (reference
    operators/graph_send_recv.py routes to the same kernel)."""
    from paddle_tpu.geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling: iterate geometric.sample_neighbors
    over `sample_sizes` hops and reindex the union subgraph (reference
    operators/graph_khop_sampler.py)."""
    from paddle_tpu.geometric import reindex_graph, sample_neighbors

    # hop h samples around the previous hop's frontier; reindex pairs
    # every seed (all hops concatenated) with its own neighbor count
    seeds_per_hop, all_neighbors, all_counts = [], [], []
    nodes = input_nodes
    for size in sample_sizes:
        neigh, counts = sample_neighbors(row, colptr, nodes,
                                         sample_size=size)
        seeds_per_hop.append(nodes)
        all_neighbors.append(neigh)
        all_counts.append(counts)
        nodes = neigh
    seeds = paddle_concat(seeds_per_hop)
    neighbors = paddle_concat(all_neighbors)
    counts = paddle_concat(all_counts)
    reindex_src, reindex_dst, out_nodes = reindex_graph(
        seeds, neighbors, counts)
    return reindex_src, reindex_dst, out_nodes, counts


def paddle_concat(xs):
    import paddle_tpu
    return paddle_tpu.concat(xs, axis=0)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    from paddle_tpu.geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes,
                            sample_size=sample_size, eids=eids,
                            return_eids=return_eids)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from paddle_tpu.geometric import reindex_graph
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def convert_out_size_to_list(out_size):
    """Reference incubate/operators/graph_*.py helper — shared with
    geometric.message_passing."""
    from paddle_tpu.geometric.message_passing import (
        convert_out_size_to_list as impl)
    return impl(out_size)


def get_out_size_tensor_inputs(inputs, attrs, out_size, op_type):
    from paddle_tpu.geometric.message_passing import (
        get_out_size_tensor_inputs as impl)
    return impl(inputs, attrs, out_size, op_type)
