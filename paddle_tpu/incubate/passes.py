"""paddle.incubate.passes parity (reference: incubate/passes/ip.py
fuse_resnet_unit + the @ir pass decorators).

The reference's IR passes pattern-match conv+BN+add+relu subgraphs in a
ProgramDesc and replace them with the fused resnet_unit op. Under XLA
that fusion happens in the compiler (the conv's epilogue absorbs the
BN affine/add/relu), so these entry points validate/annotate rather
than rewrite — running the pass is a no-op that returns the program
with a marker, and the fused semantics are available directly as
paddle_tpu.incubate.operators.resnet_unit.
"""
from __future__ import annotations

__all__ = ["ir", "fuse_resnet_unit", "set_resnet_unit_attrs",
           "set_resnet_unit_outputs"]


class ir:
    """Decorator namespace (reference incubate/passes/ir.py): registers
    pattern/replace pairs. XLA owns fusion, so registration records the
    pass for introspection and applies nothing."""

    _registry = {}

    @staticmethod
    def RegisterPass(function=None, input_specs=None):
        def deco(f):
            ir._registry[f.__name__] = {"fn": f, "input_specs": input_specs}
            return f
        if function is not None:
            return deco(function)
        return deco


def set_resnet_unit_attrs(resnet_unit, has_shortcut):
    """Pass helper (reference ip.py): record the fused op's attributes."""
    resnet_unit.SetAttr("fuse_add", True)
    resnet_unit.SetAttr("has_shortcut", has_shortcut)


def set_resnet_unit_outputs(resnet_unit, meta_list):
    resnet_unit.SetOutputs(meta_list)


@ir.RegisterPass
def fuse_resnet_unit(program=None):
    """conv+BN+relu(+add) -> resnet_unit (reference ip.py): XLA already
    fuses this epilogue into the convolution kernel on TPU, so the pass
    is an identity — use incubate.operators.ResNetUnit for the explicit
    fused layer."""
    return program
