"""MoE building-block utilities.

Reference: python/paddle/incubate/distributed/models/moe/utils.py
(count_by_gate, limit_by_capacity, prepare_forward) and moe_layer.py's
MoEScatter/MoEGather/AllGather/Slice autograd functions — the pieces a
hand-rolled expert-parallel layer composes.

TPU-native: token permutation is argsort + gather (one XLA sort, MXU-
friendly static shapes); the cross-rank exchange the reference does with
NCCL alltoall is the `ep`-axis all_to_all in distributed/utils/moe_utils
when called inside shard_map — these helpers do the LOCAL math and stay
correct in both eager and traced use.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply, unwrap
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "count_by_gate", "limit_by_capacity", "prepare_forward",
    "MoEScatter", "MoEGather", "AllGather", "Slice",
]


def count_by_gate(gate_idx, num_expert, world_size=1, require_pos=True,
                  group=None):
    """Per-expert token counts + the expert-sorted position permutation.

    gate_idx: [N] int expert id per token (top-1 routing granularity).
    Returns (pos, local_expert_count, global_expert_count): `pos`
    permutes tokens into expert order (stable), local counts are [E],
    global counts are the all-gathered [world_size * E] (equal to local
    tiled when no process group is active — single-program SPMD).
    """
    g = unwrap(gate_idx).reshape(-1).astype(jnp.int32)
    E = int(num_expert)
    w = max(world_size, 1)
    # gate ids span the GLOBAL expert space [0, E*world): local counts
    # are per global expert; global counts are the alltoall'd view (per
    # reference utils.py — identical content in the single-program SPMD
    # model, where the exchange is the ep-axis all_to_all inside
    # shard_map)
    local = jnp.bincount(g, length=E * w)
    pos = jnp.argsort(g, stable=True) if require_pos else None
    glob = local
    mk = lambda v: Tensor(v)  # noqa: E731
    return (None if pos is None else mk(pos)), mk(local), mk(glob)


def limit_by_capacity(expert_count, capacity, world_size=1, group=None):
    """Clip per-expert token counts at `capacity` (reference
    limit_by_capacity). Capacity-DROPPING dispatch — building the
    fixed-[E, C] expert batches where overflow tokens vanish — is
    distributed.moe.dispatch_combine / gshard_dispatch_combine; these
    utils only do the count bookkeeping."""
    c = unwrap(expert_count)
    cap = unwrap(capacity)
    return Tensor(jnp.minimum(c, cap))


def prepare_forward(gate, num_expert, world_size=1, moe_group=None):
    """The routing prologue (reference prepare_forward): counts, the
    expert-order permutation, and the flat batch size the expert FFN
    sees."""
    pos, local, glob = count_by_gate(gate, num_expert, world_size,
                                     group=moe_group)
    if world_size > 1:
        # tokens arriving at THIS rank's local experts: fold the global
        # [world * E] counts over the rank dim
        fwd_expert_count = Tensor(
            unwrap(glob).reshape(world_size, -1).sum(0))
    else:
        fwd_expert_count = local
    total = jnp.sum(unwrap(fwd_expert_count))
    try:
        fwd_batch_size = int(total)     # eager: a python int
    except jax.errors.ConcretizationTypeError:
        fwd_batch_size = total          # traced: stays a tracer (shapes
        #                                 must come from static capacity)
    return pos, local, glob, fwd_expert_count, fwd_batch_size


class _FnOp:
    """Reference-API shim: these are autograd.Function classes there;
    here the tape differentiates the jnp body, so `apply` is enough."""

    @classmethod
    def apply(cls, *args, **kw):
        return cls.forward(*args, **kw)


class MoEScatter(_FnOp):
    """Permute tokens into expert order (a pure gather: every routed
    token keeps its row). Capacity-dropping dispatch into fixed [E, C]
    expert batches is distributed.moe.dispatch_combine — mixing the two
    silently would mis-size the expert FFN, so a mismatched
    fwd_batch_size is a loud error."""

    @staticmethod
    def forward(x, pos, local_expert_count=None, global_expert_count=None,
                fwd_batch_size=None, world_size=1, group=None):
        n = int(unwrap(pos).shape[0])
        if fwd_batch_size is not None and \
                isinstance(fwd_batch_size, int) and fwd_batch_size != n:
            raise ValueError(
                f"MoEScatter permutes all {n} routed tokens; a clipped "
                f"fwd_batch_size ({fwd_batch_size}) needs the capacity-"
                "dropping dispatch (distributed.moe.dispatch_combine)")

        def fn(xv, pv):
            return jnp.take(xv, pv.astype(jnp.int32), axis=0)

        return apply(fn, x, pos)


class MoEGather(_FnOp):
    """Inverse of MoEScatter: expert-ordered rows back to token order."""

    @staticmethod
    def forward(x, pos, out_batch_size=None, world_size=1, group=None):
        def fn(xv, pv):
            n = out_batch_size or pv.shape[0]
            return jnp.zeros((n,) + xv.shape[1:], xv.dtype).at[
                pv.astype(jnp.int32)].set(xv[:pv.shape[0]])

        return apply(fn, x, pos)


class AllGather(_FnOp):
    """Gather shards along dim 0 across the group (reference AllGather).
    Inside shard_map this is lax.all_gather over the ep axis; eagerly in
    the single-program model it is identity."""

    @staticmethod
    def forward(x, rank=0, world_size=1, group=None):
        if world_size <= 1:
            return x
        axis = getattr(group, "axis", None) or "ep"
        import jax

        def fn(v):
            return jax.lax.all_gather(v, axis, tiled=True)

        return apply(fn, x)


class Slice(_FnOp):
    """This rank's dim-0 shard (reference Slice — inverse of AllGather)."""

    @staticmethod
    def forward(x, rank=0, world_size=1, group=None):
        if world_size <= 1:
            return x

        def fn(v):
            n = v.shape[0] // world_size
            return v[rank * n:(rank + 1) * n]

        return apply(fn, x)
