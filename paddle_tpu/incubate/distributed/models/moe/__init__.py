"""API mirror of paddle.incubate.distributed.models.moe (reference:
python/paddle/incubate/distributed/models/moe/__init__.py)."""
from paddle_tpu.distributed.moe import (  # noqa: F401
    BaseGate,
    GShardGate,
    MoELayer,
    NaiveGate,
    StackedExpertFFN,
    SwitchGate,
    dispatch_combine,
)
from .gate import *  # noqa: F401,F403
from paddle_tpu.incubate.distributed.models.moe.utils import (  # noqa: F401,E402
    AllGather,
    MoEGather,
    MoEScatter,
    Slice,
    count_by_gate,
    limit_by_capacity,
    prepare_forward,
)
from paddle_tpu.incubate.distributed.models.moe.grad_clip import (  # noqa: F401,E402
    ClipGradForMOEByGlobalNorm,
)
