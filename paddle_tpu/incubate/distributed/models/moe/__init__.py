"""API mirror of paddle.incubate.distributed.models.moe (reference:
python/paddle/incubate/distributed/models/moe/__init__.py)."""
from paddle_tpu.distributed.moe import (  # noqa: F401
    BaseGate,
    GShardGate,
    MoELayer,
    NaiveGate,
    StackedExpertFFN,
    SwitchGate,
    dispatch_combine,
)
from .gate import *  # noqa: F401,F403
