"""MoE-aware global-norm gradient clipping.

Reference: python/paddle/incubate/distributed/models/moe/grad_clip.py
(ClipGradForMOEByGlobalNorm): expert-parallel params exist once PER
RANK, so their grad-norm contribution must be averaged over the moe
group before entering the global norm, or the clip threshold shifts
with the ep degree.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.nn.clip import ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm)
        self.is_expert = is_expert_param_func or (lambda p: False)
        self.moe_group = moe_group
        # world size of the moe group: expert contributions divide by it
        self.moe_world = getattr(moe_group, "nranks", None) or 1

    def __call__(self, params_grads):
        def clippable(p, g):
            return g is not None and getattr(p, "need_clip", True)

        sq_normal = 0.0
        sq_expert = 0.0
        for p, g in params_grads:
            if not clippable(p, g):
                continue
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            if self.is_expert(p):
                sq_expert = sq_expert + s
            else:
                sq_normal = sq_normal + s
        total = jnp.sqrt(sq_normal + sq_expert / float(self.moe_world))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(total, 1e-12),
                            1.0)
        out = []
        for p, g in params_grads:
            if not clippable(p, g):
                out.append((p, g))
            else:
                from paddle_tpu.core.tensor import Tensor
                out.append((p, Tensor(g._value * scale.astype(
                    g._value.dtype))))
        return out
