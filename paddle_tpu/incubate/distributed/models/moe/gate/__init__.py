from paddle_tpu.distributed.moe import (  # noqa: F401
    BaseGate,
    GShardGate,
    NaiveGate,
    SwitchGate,
)
