"""paddle.incubate.distributed.fleet parity (reference re-exports the
fleet recompute entries)."""
from paddle_tpu.distributed.fleet.recompute_api import (  # noqa: F401
    recompute_hybrid,
    recompute_sequential,
)

__all__ = ["recompute_sequential", "recompute_hybrid"]
