"""paddle.incubate.distributed.utils parity namespace."""
from paddle_tpu.incubate.distributed.utils import io  # noqa: F401
