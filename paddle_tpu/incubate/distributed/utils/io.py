"""Distributed save/load helpers (reference:
incubate/distributed/utils/io/dist_save.py:30 save, dist_load.py:24
load/:94 load_with_place, save_for_auto.py:34 save_for_auto_inference).

The reference gathers sharded (mp/pp) state to rank 0 before writing;
here state tensors are jax global arrays whose addressable shards
gather through the array API, so save/load defer to framework.io with a
gather step for sharded values.
"""
from __future__ import annotations

__all__ = ["save", "load", "load_with_place", "save_for_auto_inference"]


def _gather_full(value):
    """Materialize a (possibly sharded) jax array fully addressable."""
    import jax
    v = getattr(value, "_value", value)
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = v.sharding.mesh if hasattr(v.sharding, "mesh") else None
        if mesh is not None:
            rep = NamedSharding(mesh, PartitionSpec())
            return jax.device_put(v, rep)
    return v


def save(state_dict, path, gather_to=0, state_type="params", **configs):
    """Gather sharded entries, then paddle save (single artifact)."""
    import numpy as np

    import paddle_tpu
    full = {k: np.asarray(_gather_full(v)) for k, v in state_dict.items()}
    process_index = 0
    try:
        import jax
        process_index = jax.process_index()
    except Exception:
        pass
    if process_index == int(gather_to):
        paddle_tpu.save(full, path, **configs)


def load(path, place=None, **configs):
    import paddle_tpu
    return paddle_tpu.load(path, **configs)


def load_with_place(path, place=None, **configs):
    """Load then commit every tensor to `place` (reference
    dist_load.py:94). Accepts a paddle place (CPUPlace/TPUPlace) or a
    jax device."""
    import paddle_tpu
    obj = paddle_tpu.load(path, **configs)
    if place is None or not hasattr(obj, "items"):
        return obj
    import jax

    import paddle_tpu as P
    platform = getattr(place, "_platform", None) or \
        ("cpu" if type(place).__name__ == "CPUPlace" else "tpu")
    try:
        dev = jax.devices(platform)[0]
    except RuntimeError:
        dev = jax.devices()[0]
    out = {}
    for k, v in obj.items():
        t = P.to_tensor(v)
        t._set_value(jax.device_put(t._value, dev))
        out[k] = t
    return out


def save_for_auto_inference(path_prefix, dist_model, cvt2cpu=False):
    """Persist a distributed model for single-process inference
    (reference save_for_auto.py:34): gather every parameter full and
    write one params artifact + a meta file."""
    import numpy as np

    import paddle_tpu
    sd = dist_model.state_dict() if hasattr(dist_model, "state_dict") \
        else dict(dist_model)
    full = {k: np.asarray(_gather_full(v)) for k, v in sd.items()}
    paddle_tpu.save(full, path_prefix + "_dist0.pdparams")
    import json
    import os
    meta = {"keys": sorted(full), "format": "gathered-full"}
    with open(path_prefix + ".meta.json", "w") as fh:
        json.dump(meta, fh)
    return path_prefix + "_dist0.pdparams"
