"""paddle.incubate.optimizer parity: LookAhead + ModelAverage.

Reference: python/paddle/incubate/optimizer/lookahead.py and
modelaverage.py.  Both are expressed as pure state-tensor updates with
`jnp.where` for the data-dependent triggers, so a `to_static` train step
traces them into the same single XLA program as the inner optimizer
(the reference versions emit conditional blocks into the fluid program).
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.engine import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import register_state_tensor
from paddle_tpu.optimizer.optimizer import Optimizer

from paddle_tpu.incubate.optimizer import functional  # noqa: F401
from paddle_tpu.incubate.optimizer.distributed_fused_lamb import (  # noqa: F401,E501
    DistributedFusedLamb,
)

__all__ = ["LookAhead", "ModelAverage", "functional",
           "DistributedFusedLamb"]


def _state(name, value):
    t = Tensor(jnp.asarray(value), name=name)
    t.persistable = True
    register_state_tensor(t)
    return t


class LookAhead(Optimizer):
    """slow_param <- slow_param + alpha * (fast_param - slow_param) every
    k inner-optimizer steps, then fast_param <- slow_param
    (reference lookahead.py:37)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        assert inner_optimizer is not None, "inner optimizer can not be None"
        assert 0.0 <= alpha <= 1.0, "alpha should be in [0, 1]"
        assert isinstance(k, int) and k > 0, "k should be a positive integer"
        # base init so every inherited Optimizer API (set_lr,
        # _learning_rate, _acc, state_dict plumbing) has its attributes;
        # like the reference (lookahead.py:133), LookAhead's own lr IS
        # alpha — the task lr lives on the inner optimizer
        super().__init__(learning_rate=alpha,
                         parameters=inner_optimizer._parameter_list)
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_counter = _state("lookahead_step", jnp.zeros((), jnp.int32))
        # slow weights snapshot the initial fast weights (created eagerly:
        # lazy creation inside a to_static trace could not be re-initialised
        # concretely). `+ 0` forces a DISTINCT buffer — aliasing the param's
        # would make to_static donate the same buffer twice.
        self._slow = {id(p): _state(f"{p.name}_slow",
                                    p._value.astype(jnp.float32) + 0)
                      for p in self._params()}

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        cnt = self._step_counter._value + 1
        self._step_counter._set_value(cnt)
        sync = (cnt % self.k) == 0
        for p in self._params():
            slow = self._slow[id(p)]
            new_slow = jnp.where(
                sync,
                self.alpha * p._value.astype(jnp.float32)
                + (1.0 - self.alpha) * slow._value,
                slow._value)
            slow._set_value(new_slow)
            p._set_value(jnp.where(sync, new_slow.astype(p._value.dtype),
                                   p._value))

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, self.inner_optimizer._params_grads()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_counter
        for p in self._params():
            sd[self._slow[id(p)].name] = self._slow[id(p)]
        return sd


class ModelAverage(Optimizer):
    """Windowed average of parameter trajectories (reference
    modelaverage.py): accumulate sums each step; inside `apply()` the
    parameters are swapped for sum/(accumulation count); `restore()` puts
    the live weights back.

    Window roll (reference docstring :49): when
    num_accumulates >= min_average_window and
    num_accumulates >= min(max_average_window,
    num_updates * average_window_rate), fold sum_1+sum_2 into sum_3 and
    restart the accumulation window.
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = float(average_window_rate)
        self.min_window = int(min_average_window)
        self.max_window = int(max_average_window)
        self._num_updates = _state("ma_num_updates", jnp.zeros((), jnp.int32))
        self._num_acc = _state("ma_num_acc", jnp.zeros((), jnp.int32))
        self._old_num_acc = _state("ma_old_num_acc", jnp.zeros((), jnp.int32))
        self._restore_vals = None
        for p in self._params():  # eager accumulator creation
            for s in ("sum_1", "sum_2", "sum_3"):
                self._acc(s, p, init=0.0, dtype=jnp.float32)

    @no_grad()
    def step(self):
        nu = self._num_updates._value + 1
        na = self._num_acc._value + 1
        window = jnp.minimum(
            jnp.asarray(self.max_window, jnp.float32),
            nu.astype(jnp.float32) * self.avg_rate)
        roll = (na >= self.min_window) & (na.astype(jnp.float32) >= window)
        for p in self._params():
            s1 = self._acc("sum_1", p)
            s2 = self._acc("sum_2", p)
            s3 = self._acc("sum_3", p)
            new_s1 = s1._value + p._value.astype(jnp.float32)
            s3._set_value(jnp.where(roll, new_s1 + s2._value, s3._value))
            s2._set_value(jnp.where(roll, jnp.zeros_like(s2._value),
                                    s2._value))
            s1._set_value(jnp.where(roll, jnp.zeros_like(new_s1), new_s1))
        self._old_num_acc._set_value(
            jnp.where(roll, na, self._old_num_acc._value))
        self._num_acc._set_value(jnp.where(roll, jnp.zeros_like(na), na))
        self._num_updates._set_value(nu)

    def _averaged(self, p):
        total = (self._acc("sum_1", p)._value + self._acc("sum_2", p)._value
                 + self._acc("sum_3", p)._value)
        count = (self._num_acc._value + self._old_num_acc._value).astype(
            jnp.float32)
        return jnp.where(count > 0, total / jnp.maximum(count, 1.0),
                         p._value.astype(jnp.float32)).astype(p._value.dtype)

    def apply(self, executor=None, need_restore=True):
        """Context manager swapping in the averaged weights (eval-time)."""
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._restore_vals = [(p, p._value) for p in self._params()]
            for p in self._params():
                p._set_value(self._averaged(p))
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        if self._restore_vals is not None:
            for p, v in self._restore_vals:
                p._set_value(v)
            self._restore_vals = None


def init_communicator(block=None, rank=None, ranks=None, ring_id=0):
    """Reference distributed_fused_lamb.py:27 bootstraps an NCCL ring by
    inserting comm-init ops into the startup program. The mesh owns
    communicators here: ensure the global mesh exists and return it."""
    from paddle_tpu.distributed.mesh import ensure_mesh
    return ensure_mesh()


def broadcast_parameters(block=None, parameters=None, ring_id=0):
    """Reference distributed_fused_lamb.py:73 broadcasts initial params
    from rank 0. Single-controller JAX initializes identically on every
    process (same seed/program), so this re-asserts replication by
    broadcasting each value from process 0 when multi-process."""
    import jax
    if parameters and jax.process_count() > 1:
        from paddle_tpu.distributed.collective import broadcast
        for p in parameters:
            broadcast(p, src=0)
    return parameters
