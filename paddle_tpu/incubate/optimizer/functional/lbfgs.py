"""L-BFGS minimizer as one lax.while_loop program.

Reference: python/paddle/incubate/optimizer/functional/lbfgs.py:27
(minimize_lbfgs — limited-memory two-loop recursion, strong-Wolfe line
search, same return tuple). TPU-native: the (s, y) history lives in two
fixed-shape [m, n] device buffers addressed circularly, and the two-loop
recursion is a pair of lax.fori_loop sweeps — everything, including the
line search, compiles into a single XLA while loop.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.optimizer.functional.bfgs import (
    _as_array,
    _objective_as_fn,
    _phi_maker,
)
from paddle_tpu.incubate.optimizer.functional.line_search import strong_wolfe


def _two_loop(g, S, Y, rho, head, count, gamma, m):
    """Direction -H g via the L-BFGS two-loop recursion.

    S/Y: [m, n] circular buffers; head = next write slot; count = number
    of valid pairs; gamma = y·s / y·y scaling of the seed H0.
    """
    q = g
    alphas = jnp.zeros((m,), g.dtype)

    def bwd(i, carry):
        q, alphas = carry
        # i = 0 is the NEWEST pair: slot (head - 1 - i) mod m
        slot = jnp.mod(head - 1 - i, m)
        valid = i < count
        a = rho[slot] * jnp.dot(S[slot], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * Y[slot]
        return q, alphas.at[slot].set(a)

    q, alphas = lax.fori_loop(0, m, bwd, (q, alphas))
    r = gamma * q

    def fwd(i, r):
        # oldest first: slot (head - count + i) mod m
        slot = jnp.mod(head - count + i, m)
        valid = i < count
        b = rho[slot] * jnp.dot(Y[slot], r)
        upd = (alphas[slot] - b) * S[slot]
        return r + jnp.where(valid, upd, 0.0)

    return lax.fori_loop(0, m, fwd, r)


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-8, tolerance_change=1e-8,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", max_line_search_iters=50,
                   initial_step_length=1.0, dtype="float32", name=None):
    if dtype not in ("float32", "float64"):
        raise ValueError(f"dtype must be 'float32' or 'float64', got {dtype}")
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            "only line_search_fn='strong_wolfe' is supported")
    jdt = jnp.float32 if dtype == "float32" else jnp.float64

    x0 = _as_array(initial_position, jdt)
    n = x0.shape[0]
    m = int(history_size)
    f = _objective_as_fn(objective_func, jdt)
    f_vg = jax.value_and_grad(f)

    value0, g0 = f_vg(x0)
    state = dict(
        k=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), jnp.bool_),
        is_converge=jnp.zeros((), jnp.bool_),
        nfev=jnp.ones((), jnp.int32),
        x=x0, value=value0, g=g0,
        S=jnp.zeros((m, n), jdt), Y=jnp.zeros((m, n), jdt),
        rho=jnp.zeros((m,), jdt),
        head=jnp.zeros((), jnp.int32), count=jnp.zeros((), jnp.int32),
        gamma=jnp.ones((), jdt),
    )

    def cond(s):
        return (s["k"] < max_iters) & ~s["done"]

    def body(s):
        pk = -_two_loop(s["g"], s["S"], s["Y"], s["rho"], s["head"],
                        s["count"], s["gamma"], m)
        dphi0 = jnp.dot(s["g"], pk)
        bad_dir = dphi0 >= 0
        pk = jnp.where(bad_dir, -s["g"], pk)
        dphi0 = jnp.where(bad_dir, -jnp.dot(s["g"], s["g"]), dphi0)

        alpha, value2, g2, nfev = strong_wolfe(
            _phi_maker(f_vg, s["x"], pk), s["g"],
            alpha0=initial_step_length, phi0=s["value"], dphi0=dphi0,
            max_iters=max_line_search_iters)
        sk = alpha * pk
        x2 = s["x"] + sk
        yk = g2 - s["g"]
        ys = jnp.dot(yk, sk)
        store = ys > 1e-10
        slot = s["head"]
        S2 = jnp.where(store, s["S"].at[slot].set(sk), s["S"])
        Y2 = jnp.where(store, s["Y"].at[slot].set(yk), s["Y"])
        rho2 = jnp.where(store,
                         s["rho"].at[slot].set(1.0 / jnp.where(store, ys, 1.0)),
                         s["rho"])
        head2 = jnp.where(store, jnp.mod(slot + 1, m), slot)
        count2 = jnp.where(store, jnp.minimum(s["count"] + 1, m), s["count"])
        gamma2 = jnp.where(store, ys / jnp.maximum(jnp.dot(yk, yk), 1e-30),
                           s["gamma"])

        g_inf = jnp.max(jnp.abs(g2))
        converged = g_inf < tolerance_grad
        stalled = (jnp.max(jnp.abs(sk)) < tolerance_change) | \
            (jnp.abs(value2 - s["value"]) < tolerance_change)
        return dict(
            k=s["k"] + 1, done=converged | stalled,
            is_converge=s["is_converge"] | converged,
            nfev=s["nfev"] + nfev,
            x=x2, value=value2, g=g2,
            S=S2, Y=Y2, rho=rho2, head=head2, count=count2, gamma=gamma2,
        )

    state["is_converge"] = jnp.max(jnp.abs(g0)) < tolerance_grad
    state["done"] = state["is_converge"]
    out = lax.while_loop(cond, body, state)
    return (Tensor(out["is_converge"].reshape(1)),
            Tensor(out["nfev"].astype(jnp.int64).reshape(1)),
            Tensor(out["x"]),
            Tensor(out["value"].reshape(1)),
            Tensor(out["g"]))
