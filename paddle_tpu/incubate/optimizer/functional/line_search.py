"""Strong-Wolfe line search as ONE lax.while_loop.

Reference: python/paddle/incubate/optimizer/functional/line_search.py
(strong_wolfe — Nocedal & Wright, Numerical Optimization 2e, Algorithms
3.5 bracketing / 3.6 zoom).

TPU-native: the reference builds the search out of nested static-graph
while ops; here the bracket and zoom phases are a single
``lax.while_loop`` state machine — each iteration evaluates phi at one
trial step (bracket phase probes a growing alpha, zoom bisects), so the
whole search compiles to one XLA loop with a single value_and_grad call
in its body.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def cubic_interpolation_(x1, f1, g1, x2, f2, g2):
    """Minimizer of the cubic through (x1,f1,g1),(x2,f2,g2) (reference
    line_search.py cubic_interpolation_, Nocedal eq. 3.59), safeguarded
    to the bracket; falls back to bisection when the cubic has no real
    minimizer in the interval."""
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    rad = d1 * d1 - g1 * g2
    ok = rad >= 0
    d2 = jnp.sign(x2 - x1) * jnp.sqrt(jnp.maximum(rad, 0.0))
    denom = g2 - g1 + 2 * d2
    xmin = x2 - (x2 - x1) * (g2 + d2 - d1) / denom
    lo = jnp.minimum(x1, x2)
    hi = jnp.maximum(x1, x2)
    bisect = 0.5 * (lo + hi)
    good = ok & jnp.isfinite(xmin) & (jnp.abs(denom) > 1e-32)
    return jnp.clip(jnp.where(good, xmin, bisect), lo, hi)


def check_input_type(input, name, op_name):
    """Reference utils.py check_input_type: tensors only."""
    import paddle_tpu
    if not isinstance(input, (paddle_tpu.Tensor, jnp.ndarray)):
        raise ValueError(f"The input {name} of {op_name} must be a "
                         f"Tensor, got {type(input)}")


def check_initial_inverse_hessian_estimate(H0):
    """Reference bfgs utils: H0 must be symmetric positive definite."""
    import numpy as np
    H = np.asarray(getattr(H0, "_value", H0))
    if not np.allclose(H, H.T, atol=1e-5):
        raise ValueError("initial_inverse_hessian_estimate must be "
                         "symmetric")
    try:
        np.linalg.cholesky(H)
    except np.linalg.LinAlgError:
        raise ValueError("initial_inverse_hessian_estimate must be "
                         "positive definite") from None


def strong_wolfe(phi_fn, g_example, alpha0=1.0, phi0=None, dphi0=None,
                 c1=1e-4, c2=0.9, max_iters=50, alpha_max=1e3):
    """Find alpha satisfying the strong Wolfe conditions.

    phi_fn(alpha) -> (phi, dphi, g): line value, line derivative and the
    full gradient at ``x + alpha * p`` (returned so the caller reuses it
    for the quasi-Newton update without another gradient evaluation).

    Returns (alpha_star, phi_star, g_star, n_func_evals).
    """
    dtype = jnp.asarray(phi0).dtype

    state = dict(
        i=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), jnp.bool_),
        zoom=jnp.zeros((), jnp.bool_),
        a_trial=jnp.asarray(alpha0, dtype),
        a_prev=jnp.zeros((), dtype),
        phi_prev=jnp.asarray(phi0, dtype),
        dphi_prev=jnp.asarray(dphi0, dtype),
        a_lo=jnp.zeros((), dtype),
        phi_lo=jnp.asarray(phi0, dtype),
        dphi_lo=jnp.asarray(dphi0, dtype),
        a_hi=jnp.zeros((), dtype),
        phi_hi=jnp.asarray(phi0, dtype),
        dphi_hi=jnp.asarray(dphi0, dtype),
        a_star=jnp.zeros((), dtype),
        phi_star=jnp.asarray(phi0, dtype),
        g_star=jnp.asarray(g_example, dtype),
        nfev=jnp.zeros((), jnp.int32),
    )
    phi0 = jnp.asarray(phi0, dtype)
    dphi0 = jnp.asarray(dphi0, dtype)

    def cond(s):
        return (~s["done"]) & (s["i"] < max_iters)

    def body(s):
        # zoom trial: cubic interpolation over the bracket (reference
        # alg), safeguarded away from the endpoints — degenerate cubics
        # fall back to bisection inside cubic_interpolation_
        a_cubic = cubic_interpolation_(s["a_lo"], s["phi_lo"], s["dphi_lo"],
                                       s["a_hi"], s["phi_hi"], s["dphi_hi"])
        lo = jnp.minimum(s["a_lo"], s["a_hi"])
        hi = jnp.maximum(s["a_lo"], s["a_hi"])
        margin = 0.1 * (hi - lo)
        a_zoom = jnp.clip(a_cubic, lo + margin, hi - margin)
        a = jnp.where(s["zoom"], a_zoom, s["a_trial"])
        phi, dphi, g = phi_fn(a)
        armijo_fail = phi > phi0 + c1 * a * dphi0
        curv_ok = jnp.abs(dphi) <= -c2 * dphi0

        # ---- bracket-phase transitions (Nocedal alg 3.5) ----
        br_to_zoom1 = armijo_fail | ((s["i"] > 0) & (phi >= s["phi_prev"]))
        br_accept = (~br_to_zoom1) & curv_ok
        br_to_zoom2 = (~br_to_zoom1) & (~curv_ok) & (dphi >= 0)
        br_continue = (~br_to_zoom1) & (~br_accept) & (~br_to_zoom2)

        # ---- zoom-phase transitions (alg 3.6, bisection) ----
        zo_shrink_hi = armijo_fail | (phi >= s["phi_lo"])
        zo_accept = (~zo_shrink_hi) & curv_ok
        zo_flip = (~zo_shrink_hi) & (~curv_ok) & \
            (dphi * (s["a_hi"] - s["a_lo"]) >= 0)
        # zoom interval collapsed -> bail out with the best point seen
        zo_stall = s["zoom"] & (jnp.abs(s["a_hi"] - s["a_lo"])
                                <= 1e-10 * jnp.maximum(1.0, jnp.abs(s["a_hi"])))

        in_zoom = s["zoom"]
        accept = jnp.where(in_zoom, zo_accept | zo_stall, br_accept)
        enter_zoom = (~in_zoom) & (br_to_zoom1 | br_to_zoom2)

        new = dict(s)
        new["i"] = s["i"] + 1
        new["nfev"] = s["nfev"] + 1
        new["done"] = s["done"] | accept
        new["zoom"] = in_zoom | enter_zoom
        # entering zoom: zoom1 brackets (a_prev, a); zoom2 brackets (a, a_prev)
        z1 = br_to_zoom1 & ~in_zoom
        z2 = br_to_zoom2 & ~in_zoom
        a_lo = jnp.where(z1, s["a_prev"], jnp.where(z2, a, s["a_lo"]))
        phi_lo = jnp.where(z1, s["phi_prev"], jnp.where(z2, phi, s["phi_lo"]))
        dphi_lo = jnp.where(z1, s["dphi_prev"],
                            jnp.where(z2, dphi, s["dphi_lo"]))
        a_hi = jnp.where(z1 | z2, jnp.where(z1, a, s["a_prev"]), s["a_hi"])
        phi_hi = jnp.where(z1 | z2, jnp.where(z1, phi, s["phi_prev"]),
                           s["phi_hi"])
        dphi_hi = jnp.where(z1 | z2, jnp.where(z1, dphi, s["dphi_prev"]),
                            s["dphi_hi"])
        # inside zoom: standard interval update
        a_hi = jnp.where(in_zoom & zo_shrink_hi, a, a_hi)
        phi_hi = jnp.where(in_zoom & zo_shrink_hi, phi, phi_hi)
        dphi_hi = jnp.where(in_zoom & zo_shrink_hi, dphi, dphi_hi)
        a_hi = jnp.where(in_zoom & zo_flip, s["a_lo"], a_hi)
        phi_hi = jnp.where(in_zoom & zo_flip, s["phi_lo"], phi_hi)
        dphi_hi = jnp.where(in_zoom & zo_flip, s["dphi_lo"], dphi_hi)
        move_lo = in_zoom & (~zo_shrink_hi) & (~zo_accept)
        a_lo = jnp.where(move_lo, a, a_lo)
        phi_lo = jnp.where(move_lo, phi, phi_lo)
        dphi_lo = jnp.where(move_lo, dphi, dphi_lo)
        new.update(a_lo=a_lo, phi_lo=phi_lo, dphi_lo=dphi_lo,
                   a_hi=a_hi, phi_hi=phi_hi, dphi_hi=dphi_hi)
        # bracket phase bookkeeping
        new["a_prev"] = jnp.where(br_continue & ~in_zoom, a, s["a_prev"])
        new["phi_prev"] = jnp.where(br_continue & ~in_zoom, phi,
                                    s["phi_prev"])
        new["dphi_prev"] = jnp.where(br_continue & ~in_zoom, dphi,
                                     s["dphi_prev"])
        new["a_trial"] = jnp.where(br_continue & ~in_zoom,
                                   jnp.minimum(2.0 * a, alpha_max),
                                   s["a_trial"])
        # record the accepted point (or best-so-far on stall)
        took = accept & ~s["done"]
        new["a_star"] = jnp.where(took, a, s["a_star"])
        new["phi_star"] = jnp.where(took, phi, s["phi_star"])
        new["g_star"] = jnp.where(took, g, s["g_star"])
        return new

    out = lax.while_loop(cond, body, state)
    # if the search never accepted (max_iters hit), fall back to the last
    # zoom midpoint / trial so the caller still makes progress
    fell_back = ~out["done"]
    a_fb = jnp.where(out["zoom"], 0.5 * (out["a_lo"] + out["a_hi"]),
                     out["a_trial"])
    phi_fb, g_fb = lax.cond(
        fell_back,
        lambda: (lambda r: (r[0], r[2]))(phi_fn(a_fb)),
        lambda: (out["phi_star"], out["g_star"]))
    alpha = jnp.where(fell_back, a_fb, out["a_star"])
    return alpha, phi_fb, g_fb, out["nfev"] + jnp.where(fell_back, 1, 0)
