"""BFGS minimizer as one lax.while_loop program.

Reference: python/paddle/incubate/optimizer/functional/bfgs.py:27
(minimize_bfgs — Nocedal & Wright alg 6.1, strong-Wolfe line search,
same return tuple). The reference assembles static-graph while ops; here
the entire minimization — outer quasi-Newton iteration, inner line
search, value_and_grad of the user objective — traces into a single XLA
while loop, so the whole optimization runs on-device with no host round
trips per iteration.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.incubate.optimizer.functional.line_search import strong_wolfe


def _as_array(x, dtype):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return v.astype(dtype)


def _objective_as_fn(objective_func, dtype):
    """User objective (Tensor -> scalar Tensor) as a pure array fn."""

    def f(x_arr):
        out = objective_func(Tensor(x_arr))
        v = out._value if isinstance(out, Tensor) else jnp.asarray(out)
        return v.reshape(()).astype(dtype)

    return f


def _phi_maker(f_vg, xk, pk):
    def phi_fn(alpha):
        value, grad = f_vg(xk + alpha * pk)
        return value, jnp.dot(grad, pk), grad

    return phi_fn


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", max_line_search_iters=50,
                  initial_step_length=1.0, dtype="float32", name=None):
    if dtype not in ("float32", "float64"):
        raise ValueError(f"dtype must be 'float32' or 'float64', got {dtype}")
    if line_search_fn != "strong_wolfe":
        raise NotImplementedError(
            "only line_search_fn='strong_wolfe' is supported")
    jdt = jnp.float32 if dtype == "float32" else jnp.float64

    x0 = _as_array(initial_position, jdt)
    n = x0.shape[0]
    if initial_inverse_hessian_estimate is None:
        H0 = jnp.eye(n, dtype=jdt)
    else:
        H0 = _as_array(initial_inverse_hessian_estimate, jdt)
    f = _objective_as_fn(objective_func, jdt)
    f_vg = jax.value_and_grad(f)
    eye = jnp.eye(n, dtype=jdt)

    value0, g0 = f_vg(x0)
    state = dict(
        k=jnp.zeros((), jnp.int32),
        done=jnp.zeros((), jnp.bool_),
        is_converge=jnp.zeros((), jnp.bool_),
        nfev=jnp.ones((), jnp.int32),
        x=x0, value=value0, g=g0, H=H0,
    )

    def cond(s):
        return (s["k"] < max_iters) & ~s["done"]

    def body(s):
        pk = -s["H"] @ s["g"]
        dphi0 = jnp.dot(s["g"], pk)
        # a non-descent direction means H lost positive-definiteness
        # (numerical); restart from steepest descent
        bad_dir = dphi0 >= 0
        pk = jnp.where(bad_dir, -s["g"], pk)
        dphi0 = jnp.where(bad_dir, -jnp.dot(s["g"], s["g"]), dphi0)

        alpha, value2, g2, nfev = strong_wolfe(
            _phi_maker(f_vg, s["x"], pk), s["g"],
            alpha0=initial_step_length, phi0=s["value"], dphi0=dphi0,
            max_iters=max_line_search_iters)
        sk = alpha * pk
        x2 = s["x"] + sk
        yk = g2 - s["g"]
        ys = jnp.dot(yk, sk)
        rho = jnp.where(ys > 1e-10, 1.0 / jnp.where(ys > 1e-10, ys, 1.0),
                        0.0)
        # Hk+1 = (I - rho s y^T) Hk (I - rho y s^T) + rho s s^T; rho==0
        # (curvature failure) leaves H unchanged
        V = eye - rho * jnp.outer(sk, yk)
        H2 = jnp.where(rho > 0,
                       V @ s["H"] @ V.T + rho * jnp.outer(sk, sk), s["H"])

        g_inf = jnp.max(jnp.abs(g2))
        converged = g_inf < tolerance_grad
        stalled = (jnp.max(jnp.abs(sk)) < tolerance_change) | \
            (jnp.abs(value2 - s["value"]) < tolerance_change)
        return dict(
            k=s["k"] + 1,
            done=converged | stalled,
            is_converge=s["is_converge"] | converged,
            nfev=s["nfev"] + nfev,
            x=x2, value=value2, g=g2, H=H2,
        )

    # already at a stationary point?
    state["is_converge"] = jnp.max(jnp.abs(g0)) < tolerance_grad
    state["done"] = state["is_converge"]
    out = lax.while_loop(cond, body, state)
    return (Tensor(out["is_converge"].reshape(1)),
            Tensor(out["nfev"].astype(jnp.int64).reshape(1)),
            Tensor(out["x"]),
            Tensor(out["value"].reshape(1)),
            Tensor(out["g"]),
            Tensor(out["H"]))
