"""paddle.incubate.optimizer.functional parity namespace.

Reference: python/paddle/incubate/optimizer/functional/__init__.py
(minimize_bfgs, minimize_lbfgs).
"""
from paddle_tpu.incubate.optimizer.functional.bfgs import minimize_bfgs  # noqa: F401
from paddle_tpu.incubate.optimizer.functional.lbfgs import minimize_lbfgs  # noqa: F401
from paddle_tpu.incubate.optimizer.functional.line_search import (  # noqa: F401
    check_initial_inverse_hessian_estimate,
    check_input_type,
    cubic_interpolation_,
    strong_wolfe,
)

__all__ = ["minimize_bfgs", "minimize_lbfgs"]
