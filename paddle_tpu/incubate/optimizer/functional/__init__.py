"""paddle.incubate.optimizer.functional parity namespace.

Reference: python/paddle/incubate/optimizer/functional/__init__.py
(minimize_bfgs, minimize_lbfgs).
"""
from paddle_tpu.incubate.optimizer.functional.bfgs import minimize_bfgs  # noqa: F401
from paddle_tpu.incubate.optimizer.functional.lbfgs import minimize_lbfgs  # noqa: F401

__all__ = ["minimize_bfgs", "minimize_lbfgs"]
