"""DistributedFusedLamb — the large-batch pretraining optimizer.

Reference: python/paddle/incubate/optimizer/distributed_fused_lamb.py:83
(DistributedFusedLamb): LAMB whose optimizer states live SHARDED across
the data-parallel ranks (the reference packs every param into one flat
aligned buffer, allreduces grads, computes a single global grad norm,
clips, then each rank updates its shard and allgathers) — ZeRO-style
state sharding + fused global clipping + per-param trust ratios +
fp32 master weights.

TPU-native redesign: no flat NCCL buffer and no hand-written allgather —
each moment/master tensor is stored FLATTENED and device_put with a
``P("dp")`` NamedSharding whenever a mesh with a `dp` axis is installed,
so XLA's GSPMD keeps the state physically sharded across the dp ranks
(1/dp of the HBM per chip, the reference's memory win) and inserts the
gather/scatter collectives around the elementwise update itself. The
global grad norm is one fused reduction over every grad; the whole
step — clip, moments, trust ratios, update — traces into the train
step's single XLA program under ``to_static``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.framework.state import register_state_tensor
from paddle_tpu.optimizer.optimizer import Optimizer

__all__ = ["DistributedFusedLamb"]


class DistributedFusedLamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 alignment=128, use_master_param_norm=True,
                 gradient_accumulation_steps=1, use_master_acc_grad=True,
                 nproc_per_node=None, use_hierarchical_allreduce=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, None, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay or 0.0
        self._exclude_fn = exclude_from_weight_decay_fn
        self._use_master_param_norm = use_master_param_norm
        self._acc_steps = int(gradient_accumulation_steps)
        # reference contract: only ClipGradByGlobalNorm is accepted
        if grad_clip is not None:
            from paddle_tpu.nn.clip import ClipGradByGlobalNorm
            if not isinstance(grad_clip, ClipGradByGlobalNorm):
                raise TypeError(
                    "DistributedFusedLamb only supports "
                    "ClipGradByGlobalNorm")
            self._max_gnorm = float(grad_clip.clip_norm)
        else:
            self._max_gnorm = -1.0
        # accepted for API parity; the collective topology knobs are
        # GSPMD's job here (clip_after_allreduce: our grads are already
        # the dp-reduced values when step() runs, so clipping here IS
        # after-allreduce; nranks scaling is the loss-mean convention)
        self._clip_after_allreduce = clip_after_allreduce
        self._is_grad_scaled_by_nranks = is_grad_scaled_by_nranks
        self._alignment = alignment
        self._found_inf = Tensor(jnp.zeros((1,), jnp.bool_),
                                 name="dfl_found_inf")

    # ---- dp-sharded flat state ----
    def _dp_sharding(self):
        from paddle_tpu.distributed.mesh import get_mesh
        mesh = get_mesh()
        if mesh is not None and "dp" in mesh.shape and \
                mesh.shape["dp"] > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            return mesh, NamedSharding(mesh, PartitionSpec("dp"))
        return None, None

    def _flat_acc(self, kind, p, init_from=None):
        """Flattened fp32 state tensor, padded to the dp degree and
        device_put with a P(\"dp\") sharding when a dp mesh is active."""
        key = (kind, id(p))
        if key not in self._accumulators:
            mesh, sh = self._dp_sharding()
            n = int(p._value.size)
            dp = mesh.shape["dp"] if mesh is not None else 1
            pad = (-n) % max(dp, 1)

            def build():
                if init_from is None:
                    flat = jnp.zeros(n + pad, jnp.float32)
                else:
                    flat = jnp.pad(
                        init_from()._value.reshape(-1).astype(jnp.float32),
                        (0, pad))
                return jax.device_put(flat, sh) if sh is not None else flat

            t = Tensor(build(), name=f"{p.name}_dfl_{kind}")
            t.persistable = True
            t.__dict__["_reinit"] = build
            t.__dict__["_dfl_pad"] = pad
            register_state_tensor(t)
            self._accumulators[key] = t
        return self._accumulators[key]

    def step(self):
        from paddle_tpu.distributed import elastic
        elastic.notify_progress()
        pg = self._params_grads()
        if not pg:
            return
        grads32 = [g._value.astype(jnp.float32).reshape(-1) for _, g in pg]

        # ---- gradient accumulation (k-step) ----
        if self._acc_steps > 1:
            step_t = self._acc("dfl_step", pg[0][0], init=0.0, shape=(),
                               dtype=jnp.float32)
            step_t._set_value(step_t._value + 1.0)
            do_update = jnp.mod(step_t._value, self._acc_steps) == 0
            new_grads = []
            for (p, _), g in zip(pg, grads32):
                accg = self._flat_acc("acc_grad", p)
                summed = accg._value + jnp.pad(
                    g, (0, accg.__dict__["_dfl_pad"]))
                accg._set_value(jnp.where(do_update,
                                          jnp.zeros_like(summed), summed))
                new_grads.append(summed[:g.size] / self._acc_steps)
            grads32 = new_grads
        else:
            do_update = jnp.asarray(True)

        # ---- ONE fused global grad norm + clip scale ----
        sq = sum(jnp.sum(g * g) for g in grads32)
        gnorm = jnp.sqrt(sq)
        self._found_inf._set_value(~jnp.isfinite(gnorm).reshape(1))
        if self._max_gnorm > 0:
            scale = jnp.minimum(1.0, self._max_gnorm / (gnorm + 1e-12))
        else:
            scale = jnp.asarray(1.0, jnp.float32)
        # non-finite grads skip the update entirely (AMP contract: the
        # reference exports _found_inf for the scaler to consume)
        do_update = do_update & jnp.isfinite(gnorm)

        lr = self._lr_value()
        b1, b2 = self._beta1, self._beta2
        for (p, _), g in zip(pg, grads32):
            g = g * scale
            m = self._flat_acc("moment1", p)
            v = self._flat_acc("moment2", p)
            master = self._flat_acc("master", p,
                                    init_from=lambda p=p: p)
            pad = m.__dict__["_dfl_pad"]
            gp = jnp.pad(g, (0, pad))
            b1p = self._acc("beta1_pow", p, init=1.0, shape=(),
                            dtype=jnp.float32)
            b2p = self._acc("beta2_pow", p, init=1.0, shape=(),
                            dtype=jnp.float32)
            b1p._set_value(jnp.where(do_update, b1p._value * b1,
                                     b1p._value))
            b2p._set_value(jnp.where(do_update, b2p._value * b2,
                                     b2p._value))
            new_m = b1 * m._value + (1 - b1) * gp
            new_v = b2 * v._value + (1 - b2) * gp * gp
            mhat = new_m / (1 - b1p._value)
            vhat = new_v / (1 - b2p._value)
            upd = mhat / (jnp.sqrt(vhat) + self._epsilon)
            wd = 0.0 if (self._exclude_fn is not None
                         and self._exclude_fn(p)) else self._lamb_wd
            w32 = master._value
            upd = upd + wd * w32
            # per-param trust ratio from MASTER (fp32) norms — the
            # reference's use_master_param_norm default
            wsrc = w32 if self._use_master_param_norm else \
                jnp.pad(p._value.reshape(-1).astype(jnp.float32), (0, pad))
            w_norm = jnp.sqrt(jnp.sum(wsrc * wsrc))
            u_norm = jnp.sqrt(jnp.sum(upd * upd))
            trust = jnp.where((w_norm > 0) & (u_norm > 0),
                              w_norm / u_norm, 1.0)
            new_w = w32 - lr * trust * upd
            m._set_value(jnp.where(do_update, new_m, m._value))
            v._set_value(jnp.where(do_update, new_v, v._value))
            master._set_value(jnp.where(do_update, new_w, master._value))
            n = int(p._value.size)
            p._set_value(jnp.where(
                do_update,
                new_w[:n].reshape(p._value.shape).astype(p._value.dtype),
                p._value))
