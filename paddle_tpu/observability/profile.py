"""Whole-program roofline profiler with per-layer HLO cost attribution.

BENCH_r05 showed the train step pinned at ~98.5% HBM bandwidth with MFU
0.27 — bytes/step is the lever, but the XLA ``cost_analysis()`` totals
say nothing about WHICH layer the bytes go to.  This module closes that
gap for any whole-traced program (``StaticFunction.traced_program()``,
``LLMEngine.audit_programs()``):

- **scope threading** — ``nn.Layer.__call__`` wraps ``forward`` in a
  ``jax.named_scope`` derived from the layer tree (attribute path under
  the parent, so two Linears never collide), and ``optimizer.step``
  scopes its update math.  JAX carries the name stack through ``jvp``
  and ``transpose``, so the BACKWARD eqns of a layer land in the same
  scope as its forward — no autograd changes needed;
- **deterministic per-op cost model** — every jaxpr eqn gets analytic
  flops (2·M·N·K for ``dot_general``, kernel-volume MACs for conv,
  element counts for pointwise/reduce) and bytes (operands + results, the
  HLO bytes-accessed convention), multiplied through ``scan`` trip
  counts.  Deterministic by construction: the same program always
  yields the same numbers, which is what ``tools/perfgate.py`` gates on;
- **attribution** — eqn costs aggregate per normalized scope path;
  anything outside a scope lands in an explicit ``<unattributed>``
  bucket (the acceptance bar: >= 90% of bytes and flops attributed on
  the gpt hybrid train target);
- **roofline classification** — per-layer arithmetic intensity against
  a target :class:`ChipSpec` (compute- vs memory-bound), whole-program
  predicted step time ``max(flops/peak, bytes/bw)``, reconciled with
  measured span wall-times (:func:`reconcile`) and optional true XLA
  ``cost_analysis()`` totals (:func:`xla_cost_totals`).

Module-level imports stay light (stdlib + jax); rendering lives in
``tools/obs_report.py --roofline`` and the regression gate in
``tools/perfgate.py``.  See docs/observability.md "Roofline profiler".
"""
from __future__ import annotations

import contextlib
import re
import threading
from dataclasses import dataclass, field

import jax

__all__ = [
    "ChipSpec", "CHIP_SPECS", "LayerCost", "RooflineReport",
    "backward_scope", "current_scope", "default_chip", "eqn_cost",
    "kernel_interiors", "layer_scope", "normalize_scope",
    "profile_engine", "profile_static_function", "profile_traced",
    "reconcile", "scope", "scope_tagging", "set_scope_tagging",
    "xla_cost_totals",
]


# ------------------------------------------------------- scope threading
_TAGGING = [True]               # list, not bool: mutation without `global`
_NULL = contextlib.nullcontext()
_tls = threading.local()

# backward-replay marker (see backward_scope): "~bwd~" never appears in
# layer names, "|" stands in for "/" so the recorded path stays ONE
# name-stack component
BWD_MARKER = "~bwd~"


def set_scope_tagging(flag=True):
    """Globally enable/disable layer-scope tagging; returns previous
    value.  Off, ``layer_scope`` is a shared no-op context."""
    prev = _TAGGING[0]
    _TAGGING[0] = bool(flag)
    return prev


def scope_tagging():
    return _TAGGING[0]


def current_scope():
    """The full scope path active on this thread (``'model/fc1'``) —
    what the autograd tape records per Node so backward replay can
    re-enter it (mirror of the jax name stack, kept here because jax
    exposes no public read of its own)."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else ""


class layer_scope:
    """The one scope primitive instrumented code uses: enters a
    ``jax.named_scope`` (so traced eqns carry the name on their name
    stack) AND mirrors the full path on a host-side stack for the tape
    (:func:`current_scope`).  ``nn.Layer.__call__`` wraps ``forward``
    in one per layer; user code can open extra scopes the same way::

        with profile.scope("loss"):
            loss = F.cross_entropy(logits, labels)

    Tagging off (or an empty name) makes both halves no-ops."""

    __slots__ = ("name", "_ns", "_pushed")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        if not _TAGGING[0] or not self.name:
            self._ns = None
            self._pushed = False
            return self
        st = getattr(_tls, "stack", None)
        if st is None:
            st = _tls.stack = []
        parent = st[-1] if st else ""
        st.append(f"{parent}/{self.name}" if parent else self.name)
        self._pushed = True
        self._ns = jax.named_scope(self.name)
        self._ns.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._ns is not None:
            self._ns.__exit__(exc_type, exc, tb)
        if self._pushed:
            _tls.stack.pop()
        return False


scope = layer_scope


def backward_scope(recorded):
    """Context for replaying a tape node's pullback.

    Plain ``jax.vjp`` transposes keep the forward eqns' name stacks
    (``transpose(jvp(model))/fc1``), but custom-vjp-style backwards are
    traced FRESH at pull time with an empty stack — those eqns would
    land in ``<unattributed>``.  Re-entering the node's recorded
    forward scope under a marker component fixes exactly that case:
    :func:`normalize_scope` prefers any real components AFTER the
    marker (a survived stack wins, no double-counted path) and decodes
    the marker's embedded path only when nothing survived."""
    if not _TAGGING[0] or not recorded:
        return _NULL
    return jax.named_scope(BWD_MARKER + recorded.replace("/", "|"))


# jvp(model) / transpose(jvp(model)) / vmap(f) ... — transform wrappers
# jax stacks around scope components; stripped so forward and backward
# eqns of the same layer share one attribution key
_WRAP_RE = re.compile(r"[A-Za-z_][\w.]*\(")


def normalize_scope(stack_str):
    """``'transpose(jvp(model))/fc1'`` -> ``'model/fc1'``: drop the
    transform wrappers, keep the user scope path.  A backward-replay
    marker (see :func:`backward_scope`) yields to any real components
    after it, else decodes to its recorded forward path."""
    if not stack_str:
        return ""
    s = _WRAP_RE.sub("", stack_str).replace(")", "")
    parts = [p for p in s.split("/") if p]
    for i in range(len(parts) - 1, -1, -1):
        if parts[i].startswith(BWD_MARKER):
            rest = parts[i + 1:]
            if rest:
                parts = rest
            else:
                parts = parts[i][len(BWD_MARKER):].split("|")
            break
    return "/".join(parts)


# ------------------------------------------------------------ chip specs
@dataclass(frozen=True)
class ChipSpec:
    """Roofline parameters of one accelerator generation (the same
    numbers bench.py uses for MFU / HBM-utilization)."""

    name: str
    peak_tflops: float          # bf16 peak, TFLOP/s per chip
    hbm_gbs: float              # HBM bandwidth, GB/s per chip
    # conservative per-core VMEM budget (the figure kernlint's KL102
    # prices Pallas block buffers against); ~16 MiB across generations
    vmem_mb: float = 16.0

    @property
    def peak_flops(self):
        return self.peak_tflops * 1e12

    @property
    def bw_bytes(self):
        return self.hbm_gbs * 1e9

    @property
    def ridge(self):
        """Arithmetic intensity (flop/byte) where compute == memory."""
        return self.peak_flops / self.bw_bytes

    @property
    def vmem_bytes(self):
        return int(self.vmem_mb * (1 << 20))

    def to_dict(self):
        return {"name": self.name, "peak_tflops": self.peak_tflops,
                "hbm_gbs": self.hbm_gbs,
                "ridge_flop_per_byte": round(self.ridge, 1),
                "vmem_mb": self.vmem_mb}


CHIP_SPECS = {
    "v4": ChipSpec("TPU v4", 275.0, 1228.0),
    "v5e": ChipSpec("TPU v5e", 197.0, 819.0),
    "v5p": ChipSpec("TPU v5p", 459.0, 2765.0),
    "v6e": ChipSpec("TPU v6e", 918.0, 1640.0),
}


def default_chip():
    """The chip the roofline classifies against: the attached device
    kind when it names a known TPU, else v5e (the target platform) —
    a CPU host profiles *for* the TPU, never against its own specs."""
    try:
        kind = getattr(jax.devices()[0], "device_kind", "") or ""
    except Exception:  # noqa: BLE001 — backend init must not kill a profile
        kind = ""
    kind = kind.lower().replace(" ", "").replace("lite", "e")
    for key, spec in CHIP_SPECS.items():
        if key in kind:
            return spec
    return CHIP_SPECS["v5e"]


# ----------------------------------------------------- per-eqn cost model
def _prod(shape):
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _var_elems(v):
    aval = getattr(v, "aval", None)
    return _prod(tuple(getattr(aval, "shape", ()) or ()))


def _var_bytes(v):
    aval = getattr(v, "aval", None)
    dt = getattr(aval, "dtype", None)
    return _var_elems(v) * int(getattr(dt, "itemsize", 4) or 4)


# pointwise prims: one flop per output element
_ELEMENTWISE = frozenset((
    "abs", "add", "add_any", "and", "atan2", "ceil", "clamp", "cos",
    "cosh", "div", "eq", "erf", "erf_inv", "erfc", "exp", "expm1",
    "floor", "ge", "gt", "integer_pow", "is_finite", "le", "log",
    "log1p", "logistic", "lt", "max", "min", "mul", "ne", "neg",
    "nextafter", "not", "or", "pow", "rem", "round", "rsqrt", "select_n",
    "sign", "sin", "sinh", "sqrt", "square", "sub", "tan", "tanh",
    "xor",
))
# reductions / scans: one flop per INPUT element
_REDUCTION = frozenset((
    "argmax", "argmin", "cumlogsumexp", "cummax", "cummin", "cumprod",
    "cumsum", "reduce_and", "reduce_max", "reduce_min", "reduce_or",
    "reduce_prod", "reduce_sum", "reduce_window_max", "reduce_window_min",
    "reduce_window_sum",
))


def eqn_cost(eqn):
    """Deterministic (flops, bytes) for one jaxpr eqn.

    Bytes follow the HLO bytes-accessed convention: every non-literal
    operand is read, every result written.  Flops are analytic: MXU ops
    from their contraction volume, pointwise/reduce ops from element
    counts, everything else (layout/copy/gather ops) zero flops but
    full bytes — exactly the traffic a memory-bound step pays."""
    in_bytes = sum(_var_bytes(v) for v in eqn.invars
                   if not hasattr(v, "val"))
    out_bytes = sum(_var_bytes(v) for v in eqn.outvars)
    nbytes = in_bytes + out_bytes
    prim = eqn.primitive.name
    out_elems = sum(_var_elems(v) for v in eqn.outvars)

    if prim == "dot_general":
        (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
        lhs_shape = tuple(getattr(getattr(eqn.invars[0], "aval", None),
                                  "shape", ()) or ())
        k = _prod(lhs_shape[d] for d in lhs_c) if lhs_shape else 1
        return 2 * out_elems * k, nbytes
    if prim == "conv_general_dilated":
        rhs_shape = tuple(getattr(getattr(eqn.invars[1], "aval", None),
                                  "shape", ()) or ())
        kernel_elems = _prod(rhs_shape) if rhs_shape else 1
        dn = eqn.params.get("dimension_numbers")
        out_c_dim = getattr(dn, "rhs_spec", (0,))[0]
        out_c = rhs_shape[out_c_dim] if rhs_shape else 1
        return 2 * out_elems * max(1, kernel_elems // max(1, out_c)), nbytes
    if prim in _ELEMENTWISE:
        return out_elems, nbytes
    if prim in _REDUCTION:
        return sum(_var_elems(v) for v in eqn.invars
                   if not hasattr(v, "val")), nbytes
    return 0, nbytes


def _iter_sub_jaxprs(params):
    for v in params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for x in items:
            if hasattr(x, "eqns"):
                yield x                      # open Jaxpr
            elif hasattr(x, "jaxpr") and hasattr(x.jaxpr, "eqns"):
                yield x.jaxpr                # ClosedJaxpr


def _join(prefix, own):
    if prefix and own:
        return f"{prefix}/{own}"
    return prefix or own


def _pallas_grid_size(eqn):
    """Total grid-step count of a pallas_call (1 when unreadable)."""
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", ()) or ()
    n = 1
    for d in grid:
        try:
            n *= max(1, int(d))
        except (TypeError, ValueError):
            pass
    return n


def _walk(jaxpr, prefix, mult, sink):
    """Accumulate ``sink[scope] = [flops, bytes, n_eqns]`` over `jaxpr`.

    Container eqns (scan/while/cond/pjit/custom_*) contribute their
    BODY's cost — the container's own operands alias the body inputs,
    so counting both would double the traffic.  ``scan`` bodies
    multiply by the trip count; ``while`` bodies count once (trip count
    is data-dependent — documented under-estimate); ``cond`` takes its
    most expensive branch (only one runs).

    ``pallas_call`` is the one container costed at its CALL BOUNDARY:
    a fused kernel's HBM traffic is its operands + results — the body
    describes per-block VMEM/register ops that never round-trip HBM,
    and walking it for bytes would both double-count (block reads) and
    erase exactly the fusion the kernel exists for.  The body is still
    walked for FLOPS (x grid steps), and the whole cost lands in the
    CALLER's scope path (the eqn's own name stack), so a fused LN never
    falls into ``<unattributed>``."""
    for eqn in jaxpr.eqns:
        own = normalize_scope(str(eqn.source_info.name_stack))
        path = _join(prefix, own)
        prim = eqn.primitive.name
        subs = list(_iter_sub_jaxprs(eqn.params))
        if prim == "pallas_call":
            flops = 0
            grid = _pallas_grid_size(eqn)
            for sub in subs:
                trial = {}
                _walk(sub, path, mult * grid, trial)
                flops += sum(v[0] for v in trial.values())
            _zero, nbytes = eqn_cost(eqn)
            agg = sink.setdefault(path, [0, 0, 0])
            agg[0] += flops
            agg[1] += nbytes * mult
            agg[2] += 1
            continue
        if subs:
            m = mult
            if prim == "scan":
                m = mult * max(1, int(eqn.params.get("length", 1) or 1))
            if prim == "cond":
                best, best_bytes = None, -1
                for sub in subs:
                    trial = {}
                    _walk(sub, path, m, trial)
                    b = sum(v[1] for v in trial.values())
                    if b > best_bytes:
                        best, best_bytes = trial, b
                for k, (f, b, n) in (best or {}).items():
                    agg = sink.setdefault(k, [0, 0, 0])
                    agg[0] += f
                    agg[1] += b
                    agg[2] += n
            else:
                for sub in subs:
                    _walk(sub, path, m, sink)
            continue
        flops, nbytes = eqn_cost(eqn)
        agg = sink.setdefault(path, [0, 0, 0])
        agg[0] += flops * mult
        agg[1] += nbytes * mult
        agg[2] += 1


def _iter_eqns_rec(jaxpr):
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _iter_sub_jaxprs(eqn.params):
            yield from _iter_eqns_rec(sub)


def kernel_interiors(closed_jaxpr, chip=None):
    """Opt-in per-kernel INTERIOR roofline rows — the dual of the
    call-boundary cost ``_walk`` books for ``pallas_call``.

    The boundary row says what a fused kernel costs the *program*
    (operands + results over HBM); the interior row says what each grid
    step moves through *VMEM* (one copy of every in/out block) and the
    arithmetic intensity the kernel body achieves against that traffic.
    ``reuse_factor`` = interior bytes / boundary bytes — how many times
    the kernel re-touches each HBM byte inside VMEM, i.e. exactly the
    reuse that justifies fusing (a factor near 1.0 means the kernel
    gains nothing over the unfused composition)."""
    chip = chip or default_chip()
    from paddle_tpu.analysis.vmem_model import estimate_vmem
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    rows = []
    for eqn in _iter_eqns_rec(jaxpr):
        if eqn.primitive.name != "pallas_call":
            continue
        grid = _pallas_grid_size(eqn)
        flops = 0
        for sub in _iter_sub_jaxprs(eqn.params):
            trial = {}
            _walk(sub, "", grid, trial)
            flops += sum(v[0] for v in trial.values())
        est = estimate_vmem(eqn)
        per_step = sum(one for _o, one, _b in est.blocks)
        interior_bytes = per_step * max(1, grid)
        _zero, boundary_bytes = eqn_cost(eqn)
        name = (str(eqn.params.get("name_and_src_info", "") or "")
                .split(" at ")[0]) or "<kernel>"
        intensity = flops / interior_bytes if interior_bytes else 0.0
        rows.append({
            "kernel": name,
            "grid_steps": int(max(1, grid)),
            "vmem_step_bytes": int(per_step),
            "interior_bytes": int(interior_bytes),
            "boundary_bytes": int(boundary_bytes),
            "flops": int(flops),
            "interior_intensity": round(intensity, 3),
            "bound": "compute" if intensity >= chip.ridge else "memory",
            "reuse_factor": round(interior_bytes / boundary_bytes, 2)
            if boundary_bytes else 0.0,
            "vmem_total_bytes": int(est.total_bytes),
            "double_buffered": bool(est.double_buffered),
        })
    return rows


# --------------------------------------------------------------- reports
UNATTRIBUTED = "<unattributed>"


@dataclass
class LayerCost:
    """Aggregated cost of one scope path (one layer, usually)."""

    name: str
    flops: int = 0
    bytes: int = 0
    n_eqns: int = 0

    @property
    def intensity(self):
        """Arithmetic intensity, flop/byte."""
        return self.flops / self.bytes if self.bytes else 0.0

    def bound(self, chip):
        return "compute" if self.intensity >= chip.ridge else "memory"

    def to_dict(self, chip=None):
        d = {"name": self.name, "flops": self.flops, "bytes": self.bytes,
             "n_eqns": self.n_eqns, "intensity": round(self.intensity, 3)}
        if chip is not None:
            d["bound"] = self.bound(chip)
        return d


@dataclass
class RooflineReport:
    """Per-layer bytes/flops attribution + roofline classification of
    one whole traced program."""

    where: str
    chip: ChipSpec
    layers: list = field(default_factory=list)   # LayerCost, bytes desc
    unattributed: LayerCost = None
    xla: dict = None            # {"flops", "bytes_accessed"} | None
    measured_ms: float = None
    measured_source: str = None
    # opt-in per-kernel interior rows (kernel_interiors() dicts)
    interiors: list = None

    def __post_init__(self):
        if self.unattributed is None:
            self.unattributed = LayerCost(UNATTRIBUTED)

    # ---- totals / fractions
    @property
    def attributed_flops(self):
        return sum(l.flops for l in self.layers)

    @property
    def attributed_bytes(self):
        return sum(l.bytes for l in self.layers)

    @property
    def total_flops(self):
        return self.attributed_flops + self.unattributed.flops

    @property
    def total_bytes(self):
        return self.attributed_bytes + self.unattributed.bytes

    @property
    def frac_attributed_flops(self):
        return self.attributed_flops / self.total_flops \
            if self.total_flops else 1.0

    @property
    def frac_attributed_bytes(self):
        return self.attributed_bytes / self.total_bytes \
            if self.total_bytes else 1.0

    @property
    def bound_fraction(self):
        """Fraction of attributed bytes living in memory-bound layers —
        1.0 means every byte of the program is on the HBM roofline."""
        if not self.attributed_bytes:
            return 0.0
        mem = sum(l.bytes for l in self.layers
                  if l.bound(self.chip) == "memory")
        return mem / self.attributed_bytes

    @property
    def top_layer(self):
        return self.layers[0].name if self.layers else ""

    @property
    def predicted_ms(self):
        """Roofline step-time floor on `chip`:
        ``max(flops/peak, bytes/bw)``."""
        return max(self.total_flops / self.chip.peak_flops,
                   self.total_bytes / self.chip.bw_bytes) * 1e3

    def rows(self):
        """Every bucket including ``<unattributed>``, bytes-descending
        (the rendering order obs_report uses)."""
        out = list(self.layers)
        if self.unattributed.n_eqns:
            out.append(self.unattributed)
        return sorted(out, key=lambda l: (-l.bytes, l.name))

    def to_dict(self):
        d = {
            "where": self.where,
            "chip": self.chip.to_dict(),
            "total_flops": self.total_flops,
            "total_bytes": self.total_bytes,
            "attributed_flops_pct": round(
                100.0 * self.frac_attributed_flops, 2),
            "attributed_bytes_pct": round(
                100.0 * self.frac_attributed_bytes, 2),
            "bound_fraction": round(self.bound_fraction, 4),
            "predicted_ms": round(self.predicted_ms, 6),
            "top_layer": self.top_layer,
            "layers": [l.to_dict(self.chip) for l in self.rows()],
        }
        if self.xla is not None:
            d["xla"] = self.xla
        if self.measured_ms is not None:
            d["measured_ms"] = round(self.measured_ms, 3)
            d["measured_source"] = self.measured_source
        if self.interiors:
            d["interiors"] = self.interiors
        return d

    @classmethod
    def from_dict(cls, d):
        """Rebuild from :meth:`to_dict` output (the JSONL dump path
        ``tools/obs_report.py --roofline`` renders)."""
        chip = ChipSpec(d["chip"]["name"], d["chip"]["peak_tflops"],
                        d["chip"]["hbm_gbs"])
        layers, unattributed = [], None
        for row in d.get("layers", ()):
            lc = LayerCost(row["name"], int(row["flops"]),
                           int(row["bytes"]), int(row.get("n_eqns", 0)))
            if lc.name == UNATTRIBUTED:
                unattributed = lc
            else:
                layers.append(lc)
        rep = cls(where=d.get("where", "<dump>"), chip=chip,
                  layers=sorted(layers, key=lambda l: (-l.bytes, l.name)),
                  unattributed=unattributed,
                  xla=d.get("xla"),
                  measured_ms=d.get("measured_ms"),
                  measured_source=d.get("measured_source"),
                  interiors=d.get("interiors"))
        return rep


# ---------------------------------------------------------- entry points
def profile_traced(closed_jaxpr, where="<traced program>", chip=None,
                   include_xla=False, include_interiors=False):
    """Roofline-profile one traced program: per-eqn cost model,
    attributed to the normalized ``jax.named_scope`` paths the layer
    tree threaded through tracing.  ``include_interiors=True`` adds the
    per-kernel INTERIOR rows (:func:`kernel_interiors`) next to the
    call-boundary attribution."""
    chip = chip or default_chip()
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    sink = {}
    _walk(jaxpr, "", 1, sink)
    layers, unattributed = [], LayerCost(UNATTRIBUTED)
    for path, (flops, nbytes, n) in sink.items():
        if path:
            layers.append(LayerCost(path, flops, nbytes, n))
        else:
            unattributed = LayerCost(UNATTRIBUTED, flops, nbytes, n)
    layers.sort(key=lambda l: (-l.bytes, l.name))
    rep = RooflineReport(where=where, chip=chip, layers=layers,
                         unattributed=unattributed)
    if include_xla:
        rep.xla = xla_cost_totals(closed_jaxpr)
    if include_interiors:
        rep.interiors = kernel_interiors(closed_jaxpr, chip=chip)
    return rep


def profile_static_function(fn, *args, where=None, chip=None,
                            include_xla=False, **kwargs):
    """Profile one ``@to_static`` function's signature: traces (never
    compiles or runs) via :meth:`StaticFunction.traced_program` and
    attributes the program's cost back to the model's layers."""
    jaxpr, _infos = fn.traced_program(*args, **kwargs)
    return profile_traced(
        jaxpr, where=where or f"<{getattr(fn, '__name__', 'static_fn')}>",
        chip=chip, include_xla=include_xla)


def profile_engine(engine, chip=None, include_xla=False):
    """{program_name: RooflineReport} over every program the serving
    engine will ever compile (``LLMEngine.audit_programs()``)."""
    return {
        name: profile_traced(jaxpr, where=f"<serving {name}>", chip=chip,
                             include_xla=include_xla)
        for name, jaxpr in engine.audit_programs().items()
    }


def xla_cost_totals(closed_jaxpr):
    """True XLA ``cost_analysis()`` totals for a traced program — the
    numbers the deterministic cost model is reconciled against.  Pays a
    real backend compile; returns None when the backend can't provide
    the analysis (the deterministic model stands alone then)."""
    try:
        fn = jax.core.jaxpr_as_fun(closed_jaxpr)
        jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                 for v in jaxpr.invars]
        ca = jax.jit(fn).lower(*avals).compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        return {"flops": float(ca.get("flops", 0.0) or 0.0),
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)
                                        or 0.0)}
    except Exception:  # noqa: BLE001 — totals are best-effort garnish
        return None


def reconcile(report, span_name, recorder=None):
    """Fill ``measured_ms`` from the span layer's per-name aggregates
    (e.g. ``jit.train_step``), so predicted-vs-measured sits in one
    report.  On a CPU host the ratio is diagnostic only — the
    prediction is for `report.chip`, the measurement for the host."""
    from paddle_tpu.observability import spans as _spans
    rec = recorder or _spans.recorder()
    agg = rec.aggregates().get(span_name)
    if agg and agg.get("count"):
        report.measured_ms = agg["total_ms"] / agg["count"]
        report.measured_source = f"span {span_name} (n={agg['count']})"
    return report
