"""Nested trace spans: always-on ring buffer + jax.profiler annotations.

``span("train_step")`` is the one annotation primitive instrumented code
uses.  It does two things:

- ALWAYS records (name, start, duration, nesting depth, thread) into a
  bounded in-process ring buffer — cheap enough (<~2 us/span: two
  monotonic clock reads and a deque append) to leave on in production,
  exportable as Chrome-trace JSON via :mod:`observability.export`;
- when a jax profiler capture is active (`profiler.in_profiler_mode()`),
  ALSO opens a ``jax.profiler.TraceAnnotation`` so the span shows up on
  the TensorBoard/Perfetto timeline next to the XLA device activity.

Spans inside a ``to_static``-traced function fire at TRACE time (host
side), which is exactly when the interesting wall-clock cost (retrace +
compile) is paid; the per-execution device time is the profiler's job.

``set_enabled(False)`` turns span recording into a near-free boolean
check — the bench overhead lane flips this to measure instrumentation
cost honestly.

Distributed tracing (docs/observability.md "Fleet tracing"): a
:class:`TraceContext` names one end-to-end request trace.  Install one
ambiently with :class:`use_context` (thread-local), or pass it to a
single span via ``span(..., ctx=...)`` — every span closed under a
context records the trace id, a fresh span id, and its parent span id,
and NESTED spans automatically parent to it.  With no context set
(the default everywhere outside the serving fleet) nothing changes:
one extra thread-local read per span.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
from collections import deque

__all__ = [
    "span", "SpanRecord", "SpanRecorder", "recorder",
    "set_enabled", "enabled",
    "TraceContext", "use_context", "current_context",
]

_state = [True]                 # list, not bool: mutation without `global`
_tls = threading.local()
_span_seq = itertools.count(1)


def _new_span_id():
    # unique across processes (fleet spools merge): pid + local counter
    return f"{os.getpid():x}.{next(_span_seq)}"


class TraceContext:
    """Identity of one distributed trace: ``(trace_id,
    parent_span_id)``.  Generated once per request at admission
    (:meth:`new`), then carried across processes on the KV-RPC wire
    envelope / handoff blob and re-installed with :class:`use_context`
    so every replica's spans land under the originating request's
    trace id."""

    __slots__ = ("trace_id", "parent_span_id")

    def __init__(self, trace_id, parent_span_id=None):
        self.trace_id = str(trace_id)
        self.parent_span_id = (None if parent_span_id is None
                               else str(parent_span_id))

    @classmethod
    def new(cls, hint=None):
        tid = uuid.uuid4().hex[:16]
        return cls(f"{hint}-{tid}" if hint else tid)

    def to_dict(self):
        return {"t": self.trace_id, "s": self.parent_span_id}

    @classmethod
    def from_dict(cls, d):
        if not d:
            return None
        return cls(d["t"], d.get("s"))

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, "
                f"parent={self.parent_span_id!r})")


def current_context():
    """The thread's ambient :class:`TraceContext` (or None)."""
    return getattr(_tls, "ctx", None)


class use_context:
    """Install `ctx` as the thread's ambient trace context for the
    ``with`` scope (``None`` clears it — safe to pass through).  Spans
    opened inside record under it; the previous context is restored on
    exit, so nesting is safe."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, exc_type, exc, tb):
        _tls.ctx = self._prev
        return False


def set_enabled(flag=True):
    """Globally enable/disable span recording; returns previous value."""
    prev = _state[0]
    _state[0] = bool(flag)
    return prev


def enabled():
    return _state[0]


class SpanRecord:
    """One closed span (times in ns, perf_counter_ns clock base).
    ``trace_id``/``span_id``/``parent_id`` are set only for spans
    closed under a :class:`TraceContext`."""

    __slots__ = ("name", "start_ns", "dur_ns", "depth", "thread_id",
                 "attrs", "trace_id", "span_id", "parent_id")

    def __init__(self, name, start_ns, dur_ns, depth, thread_id, attrs,
                 trace_id=None, span_id=None, parent_id=None):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.depth = depth
        self.thread_id = thread_id
        self.attrs = attrs
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def to_dict(self):
        d = {"name": self.name, "start_ns": self.start_ns,
             "dur_ns": self.dur_ns, "depth": self.depth,
             "thread_id": self.thread_id}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.trace_id is not None:
            d["trace"] = self.trace_id
            d["span"] = self.span_id
            if self.parent_id is not None:
                d["parent"] = self.parent_id
        return d

    def __repr__(self):
        return (f"SpanRecord({self.name!r}, {self.dur_ns / 1e6:.3f} ms, "
                f"depth={self.depth})")


class SpanRecorder:
    """Bounded ring buffer of closed spans + per-name aggregates.

    The buffer holds the most recent `cap` spans (deque maxlen: O(1)
    eviction); aggregates (count, total ns) are kept per name so the
    metrics report can summarize even spans the ring has dropped."""

    def __init__(self, cap=4096):
        # spans close on any thread (thread_id is part of the record);
        # the counter/aggregate read-modify-writes need a guard
        self._lock = threading.Lock()
        self._buf = deque(maxlen=int(cap))
        self._agg = {}              # name -> [count, total_ns]
        self._sinks = ()            # immutable tuple: lock-free read
        self.total_recorded = 0

    @property
    def capacity(self):
        return self._buf.maxlen

    def set_capacity(self, cap):
        with self._lock:
            self._buf = deque(self._buf, maxlen=int(cap))

    def add_sink(self, fn):
        """Attach ``fn(SpanRecord)``, called on every record — the
        fleet telemetry spool's tap.  Sinks run OUTSIDE the recorder
        lock (they do file IO) and a raising sink is dropped from the
        record path's fast tuple read only by :meth:`remove_sink`."""
        with self._lock:
            self._sinks = self._sinks + (fn,)

    def remove_sink(self, fn):
        with self._lock:
            self._sinks = tuple(s for s in self._sinks if s is not fn)

    def record(self, rec):
        with self._lock:
            self.total_recorded += 1
            self._buf.append(rec)
            agg = self._agg.get(rec.name)
            if agg is None:
                self._agg[rec.name] = [1, rec.dur_ns]
            else:
                agg[0] += 1
                agg[1] += rec.dur_ns
        for s in self._sinks:       # tuple snapshot: safe lock-free
            try:
                s(rec)
            except Exception:
                pass                # a broken spool must not kill serving

    def spans(self):
        """Snapshot list of buffered spans, oldest first."""
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self):
        return self.total_recorded - len(self._buf)

    def aggregates(self):
        """{name: {"count": n, "total_ms": t}} over EVERY recorded span
        (including ones the ring buffer has since evicted)."""
        with self._lock:
            items = [(name, c, ns)
                     for name, (c, ns) in sorted(self._agg.items())]
        return {name: {"count": c, "total_ms": round(ns / 1e6, 3)}
                for name, c, ns in items}

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._agg.clear()
            self.total_recorded = 0


_RECORDER = SpanRecorder()


def recorder():
    """THE process-wide span ring buffer (module singleton)."""
    return _RECORDER


class span:
    """Context manager: ``with span("serving.decode", batch=8): ...``.

    Reentrant by construction (each ``with`` entry uses its own
    instance); nesting depth is tracked per thread.  ``ctx`` ties the
    span to a :class:`TraceContext` explicitly; with no ``ctx`` the
    thread's ambient context (see :class:`use_context`) applies, and
    with neither the record carries no trace identity — exactly the
    pre-tracing behavior."""

    __slots__ = ("name", "attrs", "_t0", "_depth", "_ann", "_ctx",
                 "_sid", "_prev")

    def __init__(self, name, ctx=None, **attrs):
        self.name = name
        self.attrs = attrs or None
        self._ctx = ctx

    @property
    def span_id(self):
        """This span's id under its trace (None untraced / unentered)."""
        return getattr(self, "_sid", None)

    def __enter__(self):
        if not _state[0]:
            self._t0 = None
            return self
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self._depth = depth
        ctx = self._ctx
        if ctx is None:
            ctx = getattr(_tls, "ctx", None)
        if ctx is not None:
            self._ctx = ctx
            self._sid = _new_span_id()
            # nested spans parent to THIS span for the with scope
            self._prev = getattr(_tls, "ctx", None)
            _tls.ctx = TraceContext(ctx.trace_id, self._sid)
        else:
            self._sid = None
        self._ann = None
        # under an active jax capture the span also lands on the
        # device-side timeline; import resolved lazily once so a bare
        # `observability` import stays light
        if _in_profiler_mode():
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        dur = time.perf_counter_ns() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        _tls.depth = self._depth
        if self._sid is not None:
            _tls.ctx = self._prev
            ctx = self._ctx
            _RECORDER.record(SpanRecord(
                self.name, self._t0, dur, self._depth,
                threading.get_ident(), self.attrs,
                trace_id=ctx.trace_id, span_id=self._sid,
                parent_id=ctx.parent_span_id))
        else:
            _RECORDER.record(SpanRecord(
                self.name, self._t0, dur, self._depth,
                threading.get_ident(), self.attrs))
        return False


def _in_profiler_mode():
    # bound lazily: paddle_tpu.profiler imports the observability
    # registry inside its shim functions, so a module-level circular
    # import is avoided by resolving the flag holder on first use
    global _profiler_flag
    if _profiler_flag is None:
        from paddle_tpu import profiler
        _profiler_flag = profiler._profiler_mode
    return _profiler_flag[0]


_profiler_flag = None
