"""Nested trace spans: always-on ring buffer + jax.profiler annotations.

``span("train_step")`` is the one annotation primitive instrumented code
uses.  It does two things:

- ALWAYS records (name, start, duration, nesting depth, thread) into a
  bounded in-process ring buffer — cheap enough (<~2 us/span: two
  monotonic clock reads and a deque append) to leave on in production,
  exportable as Chrome-trace JSON via :mod:`observability.export`;
- when a jax profiler capture is active (`profiler.in_profiler_mode()`),
  ALSO opens a ``jax.profiler.TraceAnnotation`` so the span shows up on
  the TensorBoard/Perfetto timeline next to the XLA device activity.

Spans inside a ``to_static``-traced function fire at TRACE time (host
side), which is exactly when the interesting wall-clock cost (retrace +
compile) is paid; the per-execution device time is the profiler's job.

``set_enabled(False)`` turns span recording into a near-free boolean
check — the bench overhead lane flips this to measure instrumentation
cost honestly.
"""
from __future__ import annotations

import threading
import time
from collections import deque

__all__ = [
    "span", "SpanRecord", "SpanRecorder", "recorder",
    "set_enabled", "enabled",
]

_state = [True]                 # list, not bool: mutation without `global`
_tls = threading.local()


def set_enabled(flag=True):
    """Globally enable/disable span recording; returns previous value."""
    prev = _state[0]
    _state[0] = bool(flag)
    return prev


def enabled():
    return _state[0]


class SpanRecord:
    """One closed span (times in ns, perf_counter_ns clock base)."""

    __slots__ = ("name", "start_ns", "dur_ns", "depth", "thread_id",
                 "attrs")

    def __init__(self, name, start_ns, dur_ns, depth, thread_id, attrs):
        self.name = name
        self.start_ns = start_ns
        self.dur_ns = dur_ns
        self.depth = depth
        self.thread_id = thread_id
        self.attrs = attrs

    def to_dict(self):
        d = {"name": self.name, "start_ns": self.start_ns,
             "dur_ns": self.dur_ns, "depth": self.depth,
             "thread_id": self.thread_id}
        if self.attrs:
            d["attrs"] = self.attrs
        return d

    def __repr__(self):
        return (f"SpanRecord({self.name!r}, {self.dur_ns / 1e6:.3f} ms, "
                f"depth={self.depth})")


class SpanRecorder:
    """Bounded ring buffer of closed spans + per-name aggregates.

    The buffer holds the most recent `cap` spans (deque maxlen: O(1)
    eviction); aggregates (count, total ns) are kept per name so the
    metrics report can summarize even spans the ring has dropped."""

    def __init__(self, cap=4096):
        # spans close on any thread (thread_id is part of the record);
        # the counter/aggregate read-modify-writes need a guard
        self._lock = threading.Lock()
        self._buf = deque(maxlen=int(cap))
        self._agg = {}              # name -> [count, total_ns]
        self.total_recorded = 0

    @property
    def capacity(self):
        return self._buf.maxlen

    def set_capacity(self, cap):
        with self._lock:
            self._buf = deque(self._buf, maxlen=int(cap))

    def record(self, rec):
        with self._lock:
            self.total_recorded += 1
            self._buf.append(rec)
            agg = self._agg.get(rec.name)
            if agg is None:
                self._agg[rec.name] = [1, rec.dur_ns]
            else:
                agg[0] += 1
                agg[1] += rec.dur_ns

    def spans(self):
        """Snapshot list of buffered spans, oldest first."""
        with self._lock:
            return list(self._buf)

    @property
    def dropped(self):
        return self.total_recorded - len(self._buf)

    def aggregates(self):
        """{name: {"count": n, "total_ms": t}} over EVERY recorded span
        (including ones the ring buffer has since evicted)."""
        with self._lock:
            items = [(name, c, ns)
                     for name, (c, ns) in sorted(self._agg.items())]
        return {name: {"count": c, "total_ms": round(ns / 1e6, 3)}
                for name, c, ns in items}

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._agg.clear()
            self.total_recorded = 0


_RECORDER = SpanRecorder()


def recorder():
    """THE process-wide span ring buffer (module singleton)."""
    return _RECORDER


class span:
    """Context manager: ``with span("serving.decode", batch=8): ...``.

    Reentrant by construction (each ``with`` entry uses its own
    instance); nesting depth is tracked per thread."""

    __slots__ = ("name", "attrs", "_t0", "_depth", "_ann")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs or None

    def __enter__(self):
        if not _state[0]:
            self._t0 = None
            return self
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        self._depth = depth
        self._ann = None
        # under an active jax capture the span also lands on the
        # device-side timeline; import resolved lazily once so a bare
        # `observability` import stays light
        if _in_profiler_mode():
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        dur = time.perf_counter_ns() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        _tls.depth = self._depth
        _RECORDER.record(SpanRecord(
            self.name, self._t0, dur, self._depth,
            threading.get_ident(), self.attrs))
        return False


def _in_profiler_mode():
    # bound lazily: paddle_tpu.profiler imports the observability
    # registry inside its shim functions, so a module-level circular
    # import is avoided by resolving the flag holder on first use
    global _profiler_flag
    if _profiler_flag is None:
        from paddle_tpu import profiler
        _profiler_flag = profiler._profiler_mode
    return _profiler_flag[0]


_profiler_flag = None
