"""Fleet tracing: durable per-rank telemetry spools, cross-process
trace aggregation, and the crash flight recorder.

The per-process observability plane (spans ring, metrics registry,
recompile log) dies with its process — a SIGKILLed replica takes its
whole story with it.  This module makes the story durable and
fleet-wide (docs/observability.md "Fleet tracing & flight recorder"):

- :class:`TelemetrySpool` — an append-mode, per-line-flushed JSONL
  file per process under ``PTPU_OBS_SPOOL_DIR`` (the same kill-safe
  discipline :mod:`paddle_tpu.analysis.kv_tracer` proved under
  SIGKILL: a crash loses at most the in-flight line, and readers skip
  torn tails).  Arming taps the span recorder and recompile log via
  their sinks and snapshots the metrics registry periodically, so
  spans / compile events / metric snapshots stream to disk as they
  happen.
- **Clock-offset handshake** — each rank publishes a simultaneous
  ``(perf_counter_ns, wall_ns)`` anchor pair on the coordination KV at
  arm time and reads the reference rank's, recording the offset that
  maps its private ``perf_counter`` epoch onto the reference rank's
  timeline (the cross-process alignment
  :func:`observability.export.chrome_trace` cannot do alone).
- :func:`merge_spools` — all rank spools merged into one
  :class:`FleetTelemetry`: a Chrome trace with one track per process
  on aligned clocks, a rank-labeled merged metrics exposition, and
  per-request end-to-end timelines that decompose TTFT into
  queue-wait / prefill / handoff / adoption / decode stages
  (``tools/obs_report.py --fleet <dir> [--request <id>]``).
- :func:`flight_record` — the post-mortem the controller writes on a
  watchdog DEAD verdict: the dead rank's last N spans, last metric
  snapshot, and in-flight request ids, recovered from its spool.

Disarm contract: spooling is near-free to turn off — span spooling is
gated by the span recorder itself (``set_enabled(False)`` stops
records, hence sink calls), every other spool write checks the same
flag, and the foreign suppression spellings ``PTPU_OBS_SPOOL=0``
/ ``false`` / ``off`` / ``no`` make :func:`arm_from_env` a no-op.
"""
from __future__ import annotations

import json
import os
import threading
import time

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import recompile as _recompile
from paddle_tpu.observability import spans as _spans

__all__ = [
    "TelemetrySpool", "FleetTelemetry", "ProcessSpool",
    "arm_spool", "arm_from_env", "disarm", "active_spool",
    "clock_handshake", "merge_spools", "read_spool",
    "request_timeline", "flight_record",
    "SPOOL_ENV", "SUPPRESS_ENV", "SUPPRESS_SPELLINGS",
]

SPOOL_ENV = "PTPU_OBS_SPOOL_DIR"
SUPPRESS_ENV = "PTPU_OBS_SPOOL"
METRICS_INTERVAL_ENV = "PTPU_OBS_SPOOL_METRICS_S"
# the spellings that all read as "off" — tested in the flagged/clean
# disarm pair so a deployment's chosen spelling actually disarms
SUPPRESS_SPELLINGS = ("0", "false", "off", "no")
CLOCK_SITE = "obs.clock"

# span names that start / finish a request on an engine — the
# flight recorder's in-flight bookkeeping
_REQ_START_SPANS = ("serving.prefill", "serving.adopt",
                    "serving.page_import")
_REQ_FINISH_SPANS = ("serving.finish",)

_active = [None]                # list, not var: mutation without `global`


def active_spool():
    """The process's armed :class:`TelemetrySpool` (or None)."""
    return _active[0]


def _clock_key(namespace, rank):
    return f"{namespace}/obs/clock/r{int(rank)}"


class TelemetrySpool:
    """One process's durable telemetry stream: append-mode JSONL,
    flushed per line (kill-safe — a SIGKILL loses at most the line in
    flight).  Event kinds: ``meta`` (first line), ``clock`` (anchor /
    handshake), ``span``, ``recompile``, ``metrics``."""

    def __init__(self, spool_dir, rank=None, tag=""):
        os.makedirs(spool_dir, exist_ok=True)
        self.rank = None if rank is None else int(rank)
        self.pid = os.getpid()
        r = "x" if self.rank is None else str(self.rank)
        suffix = f"-{tag}" if tag else ""
        self.path = os.path.join(
            spool_dir, f"spool-r{r}-p{self.pid}{suffix}.jsonl")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self.events_written = 0
        self.bytes_written = 0
        self._write({"kind": "meta", "version": 1, "rank": self.rank,
                     "pid": self.pid, "wall_time": time.time()})

    def _write(self, ev):
        # hot path (every span): compact separators, no key sorting —
        # the encode happens outside the lock, only write+flush inside
        line = json.dumps(ev, separators=(",", ":"), default=str)
        with self._lock:
            try:
                self._fh.write(line + "\n")
                self._fh.flush()
            except ValueError:      # closed mid-disarm race: drop
                return
            self.events_written += 1
            self.bytes_written += len(line) + 1

    # ------------------------------------------------------- taps
    # every tap is gated on the span-recording flag: set_enabled(False)
    # must fully disarm spooling, not just the span stream
    def note_span(self, rec):
        """SpanRecorder sink: one closed span."""
        if not _spans.enabled():
            return
        ev = rec.to_dict()
        ev["kind"] = "span"
        self._write(ev)

    def note_recompile(self, ev):
        """RecompileLog sink: one compile event."""
        if not _spans.enabled():
            return
        self._write({"kind": "recompile", "event": ev.to_dict()})

    def snapshot_metrics(self, registry=None):
        """Append one full metrics-registry snapshot (the merged
        rank-labeled exposition reads each spool's LAST snapshot)."""
        if not _spans.enabled():
            return
        reg = registry if registry is not None else _metrics.registry()
        self._write({"kind": "metrics", "t_ns": time.perf_counter_ns(),
                     "wall_time": time.time(),
                     "metrics": reg.snapshot()})

    def note_clock(self, clock_ev):
        self._write(dict(clock_ev, kind="clock"))

    def close(self):
        try:
            self._fh.close()
        except Exception:
            pass


class _MetricsPump(threading.Thread):
    """Daemon thread appending periodic metric snapshots to the spool
    — SIGKILL-compatible by construction (each snapshot is already on
    disk when the next interval starts)."""

    def __init__(self, spool, interval_s):
        super().__init__(name="obs-spool-metrics", daemon=True)
        self._spool = spool
        self._interval = float(interval_s)
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self._interval):
            try:
                self._spool.snapshot_metrics()
            except Exception:
                pass

    def stop(self):
        self._stop.set()


# -------------------------------------------------- clock handshake
def clock_handshake(client, rank, *, namespace=None, ref_rank=0,
                    timeout_s=10.0, config=None):
    """Coordination-KV clock-offset handshake: publish this process's
    simultaneous ``(perf_counter_ns, wall_ns)`` anchor under the fleet
    namespace, read the REFERENCE rank's anchor, and return a clock
    event whose ``offset_ns`` maps this process's ``perf_counter``
    readings onto the reference rank's timeline::

        t_ref = t_local + offset_ns

    Wall clocks bridge the unrelated ``perf_counter`` epochs (same
    host: exact; cross host: NTP-bounded — ``rtt_ms`` records the
    read's round trip as the uncertainty bound).  A missing reference
    anchor (ref crashed pre-handshake) degrades gracefully: the event
    carries the local anchor only and :func:`merge_spools` falls back
    to wall-anchor alignment."""
    from paddle_tpu.resilience import fleet as _fleet
    ns = namespace if namespace is not None else _fleet.coord_namespace()
    rank = int(rank)
    anchor_perf = time.perf_counter_ns()
    anchor_wall = time.time_ns()
    ev = {"rank": rank, "ref_rank": int(ref_rank),
          "anchor_perf_ns": anchor_perf, "anchor_wall_ns": anchor_wall,
          "offset_ns": None, "rtt_ms": None}
    _fleet.kv_set_bytes(client, _clock_key(ns, rank),
                        json.dumps(ev, sort_keys=True).encode())
    if rank == int(ref_rank):
        ev["offset_ns"] = 0
        ev["rtt_ms"] = 0.0
        return ev
    t0 = time.perf_counter()
    try:
        raw = _fleet.kv_get_bytes(
            client, _clock_key(ns, ref_rank), timeout_s,
            site=CLOCK_SITE, missing_rank=int(ref_rank), config=config)
        ref = json.loads(bytes(raw).decode())
    except Exception:
        return ev                   # anchor-only: merge aligns by wall
    rtt_ms = (time.perf_counter() - t0) * 1e3
    ev["offset_ns"] = ((anchor_wall - ref["anchor_wall_ns"])
                       + (ref["anchor_perf_ns"] - anchor_perf))
    ev["rtt_ms"] = round(rtt_ms, 3)
    return ev


# ------------------------------------------------------------ arming
def arm_spool(spool_dir, rank=None, *, tag="", client=None,
              namespace=None, ref_rank=0, metrics_interval_s=None,
              handshake_timeout_s=10.0, config=None):
    """Arm continuous spooling for this process: open the spool,
    record the clock anchor (KV handshake when `client` is given),
    tap the span recorder and recompile log, and start the periodic
    metrics pump when `metrics_interval_s` is set.  Idempotent-ish:
    re-arming while armed returns the existing spool."""
    if _active[0] is not None:
        return _active[0]
    spool = TelemetrySpool(spool_dir, rank=rank, tag=tag)
    if client is not None and rank is not None:
        ev = clock_handshake(client, rank, namespace=namespace,
                             ref_rank=ref_rank,
                             timeout_s=handshake_timeout_s,
                             config=config)
    else:
        # solo anchor: merge_spools aligns by wall clock if this spool
        # ever meets others
        ev = {"rank": spool.rank, "ref_rank": None,
              "anchor_perf_ns": time.perf_counter_ns(),
              "anchor_wall_ns": time.time_ns(),
              "offset_ns": None, "rtt_ms": None}
    spool.note_clock(ev)
    _spans.recorder().add_sink(spool.note_span)
    _recompile.recompile_log().add_sink(spool.note_recompile)
    spool._pump = None
    if metrics_interval_s:
        spool._pump = _MetricsPump(spool, metrics_interval_s)
        spool._pump.start()
    _active[0] = spool
    return spool


def arm_from_env(rank=None, client=None, **kw):
    """Worker-process arming (same entry points kv_tracer uses): when
    ``PTPU_OBS_SPOOL_DIR`` is set — and no suppression spelling
    (``PTPU_OBS_SPOOL=0/false/off/no``) vetoes it — arm spooling into
    that directory.  No-op (returns None) otherwise, so entry points
    call this unconditionally."""
    if os.environ.get(SUPPRESS_ENV, "").strip().lower() \
            in SUPPRESS_SPELLINGS:
        return None
    spool_dir = os.environ.get(SPOOL_ENV)
    if not spool_dir:
        return None
    interval = kw.pop("metrics_interval_s", None)
    if interval is None:
        interval = float(os.environ.get(METRICS_INTERVAL_ENV, "0.5"))
    return arm_spool(spool_dir, rank=rank, client=client,
                     metrics_interval_s=interval, **kw)


def disarm(final_snapshot=True):
    """Detach the taps, stop the pump, append one final metrics
    snapshot, and close the spool (no-op when not armed)."""
    spool = _active[0]
    if spool is None:
        return None
    _spans.recorder().remove_sink(spool.note_span)
    _recompile.recompile_log().remove_sink(spool.note_recompile)
    pump = getattr(spool, "_pump", None)
    if pump is not None:
        pump.stop()
    if final_snapshot:
        try:
            spool.snapshot_metrics()
        except Exception:
            pass
    spool.close()
    _active[0] = None
    return spool


# ----------------------------------------------------------- reading
def read_spool(path):
    """Parse one spool file, skipping torn lines (the SIGKILL tail):
    returns ``{"meta", "clock", "spans", "recompiles", "metrics",
    "torn_lines"}``."""
    out = {"meta": None, "clock": None, "spans": [], "recompiles": [],
           "metrics": [], "torn_lines": 0}
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                out["torn_lines"] += 1
                continue
            kind = ev.get("kind")
            if kind == "meta" and out["meta"] is None:
                out["meta"] = ev
            elif kind == "clock" and out["clock"] is None:
                out["clock"] = ev
            elif kind == "span":
                out["spans"].append(ev)
            elif kind == "recompile":
                out["recompiles"].append(ev)
            elif kind == "metrics":
                out["metrics"].append(ev)
    return out


class ProcessSpool:
    """One process's parsed spool + its clock offset onto the merged
    (reference-rank) timeline."""

    __slots__ = ("path", "rank", "pid", "meta", "clock", "spans",
                 "recompiles", "metrics", "torn_lines", "offset_ns")

    def __init__(self, path, parsed):
        self.path = path
        self.meta = parsed["meta"] or {}
        self.clock = parsed["clock"]
        self.spans = parsed["spans"]
        self.recompiles = parsed["recompiles"]
        self.metrics = parsed["metrics"]
        self.torn_lines = parsed["torn_lines"]
        self.rank = self.meta.get("rank")
        self.pid = self.meta.get("pid")
        self.offset_ns = 0

    @property
    def label(self):
        r = "?" if self.rank is None else self.rank
        return f"rank {r} (pid {self.pid})"


def _align(processes):
    """Compute each process's ``offset_ns`` onto the reference
    timeline: the recorded handshake offset when present, else the
    wall-anchor bridge against the reference process's anchor."""
    ref = None
    for p in processes:             # prefer the handshake's ref rank
        c = p.clock or {}
        if c.get("offset_ns") == 0 or (c.get("ref_rank") is not None
                                       and p.rank == c.get("ref_rank")):
            ref = p
            break
    if ref is None and processes:
        ref = min(processes,
                  key=lambda p: (p.rank is None, p.rank or 0, p.pid or 0))
    for p in processes:
        c = p.clock or {}
        if p is ref:
            p.offset_ns = 0
        elif c.get("offset_ns") is not None:
            p.offset_ns = int(c["offset_ns"])
        elif (c.get("anchor_perf_ns") is not None and ref is not None
              and (ref.clock or {}).get("anchor_perf_ns") is not None):
            rc = ref.clock
            p.offset_ns = ((c["anchor_wall_ns"] - rc["anchor_wall_ns"])
                           + (rc["anchor_perf_ns"] - c["anchor_perf_ns"]))
        else:
            p.offset_ns = 0
    return ref


class FleetTelemetry:
    """Every rank spool in one merged, clock-aligned view."""

    def __init__(self, processes):
        self.processes = sorted(
            processes,
            key=lambda p: (p.rank is None, p.rank or 0, p.pid or 0))
        self.ref = _align(self.processes)

    # ------------------------------------------------------ summary
    def summary(self):
        skews = [p.clock.get("rtt_ms") for p in self.processes
                 if p.clock and p.clock.get("rtt_ms")]
        return {
            "processes": len(self.processes),
            "ranks": [p.rank for p in self.processes],
            "spans": sum(len(p.spans) for p in self.processes),
            "recompiles": sum(len(p.recompiles)
                              for p in self.processes),
            "metric_snapshots": sum(len(p.metrics)
                                    for p in self.processes),
            "torn_lines": sum(p.torn_lines for p in self.processes),
            "traces": len(self.traces()),
            "ref_rank": None if self.ref is None else self.ref.rank,
            "clock_skew_ms": round(max(skews) / 2.0, 3) if skews
            else 0.0,
        }

    # ------------------------------------------------- chrome trace
    def chrome_trace(self):
        """One Chrome ``traceEvents`` dict: one pid track per process
        (aligned clocks), spans as ``ph:"X"``, compile events as
        instant markers."""
        ranks = [p.rank for p in self.processes]
        unique = (None not in ranks and len(set(ranks)) == len(ranks))
        events = []
        for i, p in enumerate(self.processes):
            pid = p.rank if unique else (p.pid or i)
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": p.label}})
            for s in p.spans:
                ev = {"name": s["name"], "cat": "span", "ph": "X",
                      "pid": pid, "tid": s.get("thread_id", 0),
                      "ts": round((s["start_ns"] + p.offset_ns) / 1e3,
                                  3),
                      "dur": round(s["dur_ns"] / 1e3, 3)}
                args = dict(s.get("attrs") or {})
                if "trace" in s:
                    args["trace"] = s["trace"]
                    args["span"] = s.get("span")
                    if s.get("parent") is not None:
                        args["parent"] = s["parent"]
                if args:
                    ev["args"] = args
                events.append(ev)
            for r in p.recompiles:
                e = r.get("event", {})
                if e.get("t_ns") is None:
                    continue
                events.append({
                    "name": f"recompile:{e.get('fn')}",
                    "cat": "recompile", "ph": "i", "s": "g",
                    "pid": pid, "tid": 0,
                    "ts": round((e["t_ns"] + p.offset_ns) / 1e3, 3),
                    "args": {"kind": e.get("kind"),
                             "cause": e.get("cause")}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path):
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.chrome_trace(), fh)
        return path

    # ---------------------------------------------- merged metrics
    def merged_metrics(self):
        """{rank: last metrics snapshot} across the fleet."""
        out = {}
        for p in self.processes:
            if p.metrics:
                key = "?" if p.rank is None else p.rank
                out[key] = p.metrics[-1]["metrics"]
        return out

    def prometheus_text(self):
        """Rank-labeled merged exposition: scalars as-is, histogram
        summaries flattened to ``_count`` / ``_p50_ms`` / ``_p99_ms``
        (summary exposition, not full buckets — the per-process scrape
        endpoint remains the high-fidelity path)."""
        lines = []
        for rank, snap in sorted(self.merged_metrics().items(),
                                 key=lambda kv: str(kv[0])):
            for key in sorted(snap):
                val = snap[key]
                name, brace, rest = key.partition("{")
                labels = f'rank="{rank}"'
                if brace:
                    inner = rest[:-1]
                    inner = ",".join(
                        f'{kv.split("=", 1)[0]}="{kv.split("=", 1)[1]}"'
                        for kv in inner.split(",") if "=" in kv)
                    labels = f"{inner},{labels}" if inner else labels
                if isinstance(val, dict):    # histogram summary
                    for k, suffix in (("count", "_count"),
                                      ("p50", "_p50_ms"),
                                      ("p99", "_p99_ms")):
                        v = val.get(k)
                        if v is not None:
                            lines.append(
                                f"{name}{suffix}{{{labels}}} {v}")
                elif isinstance(val, (int, float)):
                    lines.append(f"{name}{{{labels}}} {val}")
        return "\n".join(lines) + "\n"

    # -------------------------------------------- recompile ledger
    def recompiles_by_rank(self):
        """{rank: [recompile event dicts]} — the fleet-wide warm-boot
        zero-recompile assertion reads this (satellite: worker-process
        compile events used to vanish with the process)."""
        out = {}
        for p in self.processes:
            key = "?" if p.rank is None else p.rank
            out.setdefault(key, []).extend(
                r.get("event", {}) for r in p.recompiles)
        return out

    # ----------------------------------------------------- traces
    def _spans_with_process(self):
        for p in self.processes:
            for s in p.spans:
                yield p, s

    def traces(self):
        """{trace_id: [(process, span_dict), ...]} for every traced
        span, each list sorted by aligned start time."""
        out = {}
        for p, s in self._spans_with_process():
            t = s.get("trace")
            if t is not None:
                out.setdefault(t, []).append((p, s))
        for lst in out.values():
            lst.sort(key=lambda ps: ps[1]["start_ns"] + ps[0].offset_ns)
        return out

    def find_trace(self, request_or_trace):
        """Resolve a trace id, router rid (``rr-N``), or engine rid
        (``req-N``) to its trace id (None when unknown)."""
        want = str(request_or_trace)
        traces = self.traces()
        if want in traces:
            return want
        for tid, lst in sorted(traces.items()):
            for _p, s in lst:
                if str((s.get("attrs") or {}).get("request")) == want:
                    return tid
        return None

    def timeline(self, request_or_trace):
        """Per-request end-to-end timeline: the trace's spans across
        every process on the aligned clock, plus the TTFT stage
        decomposition (docs/observability.md "Per-request
        timelines")."""
        tid = self.find_trace(request_or_trace)
        if tid is None:
            return None
        entries = []
        for p, s in self.traces()[tid]:
            entries.append({
                "name": s["name"], "rank": p.rank, "pid": p.pid,
                "start_ns": s["start_ns"] + p.offset_ns,
                "dur_ns": s["dur_ns"],
                "span": s.get("span"), "parent": s.get("parent"),
                "attrs": s.get("attrs") or {}})

        def named(*names):
            return [e for e in entries if e["name"] in names]

        admits = named("serving.router.admit")
        prefills = named("serving.prefill")
        adopts = named("serving.adopt")
        handoffs = named("serving.page_export", "serving.page_import")
        finishes = named("serving.finish")
        stages = {}
        if admits and prefills:
            stages["queue_wait_s"] = max(
                0.0, (prefills[0]["start_ns"] - admits[0]["start_ns"])
                / 1e9)
        if prefills:
            stages["prefill_s"] = sum(e["dur_ns"]
                                      for e in prefills) / 1e9
        if handoffs:
            stages["handoff_s"] = sum(e["dur_ns"]
                                      for e in handoffs) / 1e9
        if adopts:
            stages["adoption_s"] = sum(e["dur_ns"]
                                       for e in adopts) / 1e9
        if finishes and prefills:
            last_work = max(e["start_ns"] + e["dur_ns"]
                            for e in prefills + adopts + handoffs)
            stages["decode_s"] = max(
                0.0, (finishes[0]["start_ns"] - last_work) / 1e9)
        if admits and finishes:
            stages["total_s"] = max(
                0.0, (finishes[0]["start_ns"] + finishes[0]["dur_ns"]
                      - admits[0]["start_ns"]) / 1e9)
        # the ROUTER rid names the request fleet-wide; engine rids
        # (req-N, one per hosting engine) are the fallback
        request = None
        for e in admits or (prefills + finishes):
            request = request or e["attrs"].get("request")
        return {
            "trace": tid,
            "request": request,
            "complete": bool(admits) and bool(finishes),
            "admissions": len(admits),
            "finishes": len(finishes),
            "migrations": len(adopts),
            "handoffs": len(handoffs),
            "processes": sorted({e["rank"] for e in entries
                                 if e["rank"] is not None}),
            "stages": stages,
            "spans": entries,
        }

    # ---------------------------------------------- flight recorder
    def flight_record(self, rank, last_n=50):
        """Post-mortem for `rank` from its spool: last `last_n` spans,
        last metric snapshot, and the request ids in flight on that
        engine at death (started by prefill/adopt/import, no finish
        span)."""
        procs = [p for p in self.processes if p.rank == int(rank)]
        if not procs:
            return None
        p = max(procs,
                key=lambda q: (q.meta or {}).get("wall_time", 0.0))
        started, finished = {}, set()
        for s in p.spans:
            rid = (s.get("attrs") or {}).get("request")
            if rid is None:
                continue
            if s["name"] in _REQ_START_SPANS:
                started[str(rid)] = s.get("trace")
            elif s["name"] in _REQ_FINISH_SPANS:
                finished.add(str(rid))
        in_flight = sorted(r for r in started if r not in finished)
        return {
            "rank": p.rank,
            "pid": p.pid,
            "spool": p.path,
            "torn_lines": p.torn_lines,
            "spans_total": len(p.spans),
            "last_spans": [dict(s) for s in p.spans[-int(last_n):]],
            "last_metrics": (p.metrics[-1]["metrics"] if p.metrics
                             else None),
            "in_flight_requests": in_flight,
            "in_flight_traces": {r: started[r] for r in in_flight},
            "recompiles": len(p.recompiles),
        }


def merge_spools(spool_dir):
    """Load every ``spool-*.jsonl`` under `spool_dir` (torn SIGKILL
    tails skipped) into one :class:`FleetTelemetry`."""
    procs = []
    for name in sorted(os.listdir(spool_dir)):
        if not (name.startswith("spool-") and name.endswith(".jsonl")):
            continue
        path = os.path.join(spool_dir, name)
        procs.append(ProcessSpool(path, read_spool(path)))
    return FleetTelemetry(procs)


def request_timeline(spool_dir, request_or_trace):
    """Convenience: :func:`merge_spools` + :meth:`timeline`."""
    return merge_spools(spool_dir).timeline(request_or_trace)


def flight_record(spool_dir, rank, last_n=50, write=True):
    """The controller's DEAD-verdict hook: build rank's post-mortem
    from its spool and (with `write`) persist it as
    ``postmortem-r<rank>.json`` next to the spools."""
    report = merge_spools(spool_dir).flight_record(rank, last_n=last_n)
    if report is not None and write:
        path = os.path.join(spool_dir, f"postmortem-r{int(rank)}.json")
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True,
                      default=str)
        report["path"] = path
    return report
