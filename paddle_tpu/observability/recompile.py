"""Recompile attribution: WHY did this program compile (again)?

On TPU an unexpected retrace silently costs seconds to minutes — the
central cost of whole-program XLA compilation.  The repo's two compile
choke points both report here:

- ``jit.api.StaticFunction.__call__`` on every cache miss calls
  :func:`note_jit_compile`, which diffs the new cache key against the
  NEAREST cached signature and records WHICH argument's shape / dtype /
  static leaf (or the framework state registry) changed, plus the
  wall-clock trace and compile time;
- ``serving.LLMEngine._compile`` calls :func:`note_aot_compile` for its
  planned AOT program set, so the serving compile counter and the jit
  recompile log share one timeline (and one registry counter,
  ``obs_recompile_total``).

Events land in a bounded ring buffer, are summarized into the metrics
registry (visible in ``profiler.metrics_report()``), and render through
``tools/obs_report.py`` / the JSONL exporter.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from paddle_tpu.observability import metrics as _metrics

__all__ = [
    "RecompileEvent", "RecompileLog", "recompile_log",
    "note_jit_compile", "note_aot_compile",
]


class RecompileEvent:
    """One compile event.

    ``changes`` is a list of ``{"arg", "kind", "before", "after"}``
    dicts — `kind` one of shape/dtype/static/structure/state/traced —
    empty for a first compile or a planned AOT compile."""

    __slots__ = ("seq", "wall_time", "t_ns", "fn", "kind", "cause",
                 "changes", "trace_ms", "compile_ms", "cache_size",
                 "attrs")

    def __init__(self, seq, fn, kind, cause, changes, trace_ms=None,
                 compile_ms=None, cache_size=None, attrs=None):
        self.seq = seq
        self.wall_time = time.time()
        # monotonic twin of wall_time on the SAME clock the span ring
        # buffer uses — so the Chrome-trace exporter can place compile
        # events on the span timeline (an instant marker at the step
        # where the retrace happened)
        self.t_ns = time.perf_counter_ns()
        self.fn = fn
        self.kind = kind                # "jit" | "serving-aot"
        self.cause = cause
        self.changes = changes
        self.trace_ms = trace_ms
        self.compile_ms = compile_ms
        self.cache_size = cache_size
        self.attrs = attrs or {}

    def changed_args(self):
        return [c["arg"] for c in self.changes]

    def to_dict(self):
        return {
            "seq": self.seq,
            "wall_time": round(self.wall_time, 3),
            "t_ns": self.t_ns,
            "fn": self.fn,
            "kind": self.kind,
            "cause": self.cause,
            "changes": self.changes,
            "trace_ms": self.trace_ms,
            "compile_ms": self.compile_ms,
            "cache_size": self.cache_size,
            "attrs": self.attrs,
        }

    def format(self):
        parts = [f"#{self.seq} [{self.kind}] {self.fn}: {self.cause}"]
        for c in self.changes:
            parts.append(f"    {c['arg']}: {c['kind']} "
                         f"{c['before']} -> {c['after']}")
        timing = []
        if self.trace_ms is not None:
            timing.append(f"trace {self.trace_ms:.1f} ms")
        if self.compile_ms is not None:
            timing.append(f"compile {self.compile_ms:.1f} ms")
        if timing:
            parts.append("    " + ", ".join(timing))
        return "\n".join(parts)


class RecompileLog:
    """Bounded compile-event log + the registry-backed counter."""

    def __init__(self, cap=512):
        # compile events arrive from any thread (a jit cache miss on
        # the training thread can race a serving-engine AOT compile);
        # _seq must stay unique and the counter exact
        self._lock = threading.Lock()
        self._buf = deque(maxlen=int(cap))
        self._sinks = ()            # immutable tuple: lock-free read
        self._seq = 0

    def add_sink(self, fn):
        """Attach ``fn(RecompileEvent)``, called on every record — the
        fleet telemetry spool's tap, so worker-process compile events
        survive the process (fleet-wide warm-boot assertions).  Sinks
        run outside the log lock."""
        with self._lock:
            self._sinks = self._sinks + (fn,)

    def remove_sink(self, fn):
        with self._lock:
            self._sinks = tuple(s for s in self._sinks if s is not fn)

    def record(self, fn, kind, cause, changes, **kw):
        with self._lock:
            self._seq += 1
            ev = RecompileEvent(self._seq, fn, kind, cause, changes,
                                **kw)
            self._buf.append(ev)
        _metrics.registry().counter(
            "obs_recompile_total",
            help="compile events observed (jit cache misses + AOT)").inc()
        for s in self._sinks:
            try:
                s(ev)
            except Exception:
                pass                # a broken spool must not block compiles
        return ev

    def events(self):
        with self._lock:
            return list(self._buf)

    @property
    def count(self):
        return self._seq

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._seq = 0

    def snapshot(self, last=10):
        """Metrics-source view: total count + the most recent events."""
        with self._lock:
            recent = list(self._buf)[-last:]
            count = self._seq
        return {
            "count": count,
            "recent": [e.to_dict() for e in recent],
        }


_LOG = RecompileLog()


def recompile_log():
    """THE process-wide recompile log (module singleton)."""
    return _LOG


# ------------------------------------------------------------ key diff
def _leaf_descriptors(key, array_leaf):
    """Per-leaf descriptor list for one jit cache key.

    The key is ``(in_treedef, sig, static, reg_ver)`` where `static`
    holds one entry per flattened leaf (`array_leaf` sentinel at traced
    positions) and `sig` holds (shape, dtype) per traced leaf in
    order."""
    _, sig, static, _ = key
    out, j = [], 0
    for s in static:
        if s is array_leaf:
            out.append(("array", sig[j]))
            j += 1
        else:
            out.append(("static", s))
    return out


def diff_keys(new_key, old_key, names, array_leaf):
    """Changes between two cache keys with IDENTICAL treedefs.

    `names` is one human-readable name per flattened leaf of the new
    key (same order as the static tuple)."""
    changes = []
    new_d = _leaf_descriptors(new_key, array_leaf)
    old_d = _leaf_descriptors(old_key, array_leaf)
    for i, (nd, od) in enumerate(zip(new_d, old_d)):
        if nd == od:
            continue
        name = names[i] if names and i < len(names) else f"leaf{i}"
        if nd[0] == "array" and od[0] == "array":
            (o_shape, o_dtype), (n_shape, n_dtype) = od[1], nd[1]
            if o_shape != n_shape:
                changes.append({"arg": name, "kind": "shape",
                                "before": list(o_shape),
                                "after": list(n_shape)})
            if o_dtype != n_dtype:
                changes.append({"arg": name, "kind": "dtype",
                                "before": o_dtype, "after": n_dtype})
        elif nd[0] != od[0]:
            changes.append({"arg": name, "kind": "traced",
                            "before": od[0], "after": nd[0]})
        else:
            changes.append({"arg": name, "kind": "static",
                            "before": repr(od[1]), "after": repr(nd[1])})
    if new_key[3] != old_key[3]:
        changes.append({"arg": "<state-registry>", "kind": "state",
                        "before": old_key[3], "after": new_key[3]})
    return changes


def _nearest(new_key, prior_keys, array_leaf):
    """The cached key (same treedef) with the fewest differing leaves."""
    new_d = _leaf_descriptors(new_key, array_leaf)

    def distance(k):
        old_d = _leaf_descriptors(k, array_leaf)
        d = sum(1 for a, b in zip(new_d, old_d) if a != b)
        return d + (1 if k[3] != new_key[3] else 0)

    return min(prior_keys, key=distance)


def note_jit_compile(fn_name, key, prior_keys, names, array_leaf,
                     trace_ms=None):
    """Record one StaticFunction cache miss; returns the event so the
    caller can attach the first-execution compile time afterwards."""
    prior_keys = list(prior_keys)
    if not prior_keys:
        cause, changes = "first compile of this function", []
    else:
        same_tree = [k for k in prior_keys if k[0] == key[0]]
        if not same_tree:
            cause, changes = (
                "new call structure (argument tree changed)", [])
        else:
            changes = diff_keys(key, _nearest(key, same_tree, array_leaf),
                                names, array_leaf)
            if changes:
                kinds = sorted({c["kind"] for c in changes})
                args = ", ".join(dict.fromkeys(c["arg"] for c in changes))
                cause = f"{'/'.join(kinds)} change in {args}"
            else:
                cause = "signature changed (unattributed)"
    return _LOG.record(fn_name, "jit", cause, changes, trace_ms=trace_ms,
                       cache_size=len(prior_keys) + 1)


def note_aot_compile(program, compile_ms=None, cache_size=None,
                     bound=None, engine=None):
    """Record one planned ahead-of-time compile (serving engine)."""
    attrs = {}
    if bound is not None:
        attrs["compile_bound"] = bound
    if engine is not None:
        attrs["engine"] = engine
    return _LOG.record(str(program), "serving-aot",
                       "planned AOT compile", [], compile_ms=compile_ms,
                       cache_size=cache_size, attrs=attrs)
