"""Exporters: JSONL dump, Prometheus text + scrape endpoint, Chrome trace.

Render targets for the same in-process state (span ring buffer, metrics
registry, recompile log, roofline reports):

- :func:`dump_jsonl` / :func:`load_jsonl` — one self-describing line
  per record (``{"kind": "span" | "recompile" | "metric" | "roofline" |
  "meta"}``), the interchange format ``tools/obs_report.py`` reads;
- :func:`prometheus_text` — the text exposition format (counters,
  gauges, and reservoir histograms as Prometheus `summary` quantiles)
  a scrape endpoint or node textfile collector can serve as-is;
- :func:`serve_prometheus` — a stdlib ``http.server`` on a daemon
  thread serving :func:`prometheus_text` live (``/metrics``), the
  scrape surface the multi-engine router balances admissions from;
  owned and shutdown-able (:class:`PrometheusServer`);
- :func:`chrome_trace` / :func:`write_chrome_trace` — the span buffer
  as Chrome ``traceEvents`` JSON (recompile events appear as instant
  markers on the same timeline), loadable in Perfetto /
  chrome://tracing.
"""
from __future__ import annotations

import http.server
import json
import threading
import time

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import recompile as _recompile
from paddle_tpu.observability import spans as _spans

__all__ = [
    "dump_jsonl", "load_jsonl", "prometheus_text", "chrome_trace",
    "write_chrome_trace", "serve_prometheus", "PrometheusServer",
]


# ------------------------------------------------------------------ JSONL
def dump_jsonl(path, spans=None, recompiles=None, registry=None,
               rooflines=None, capacities=None):
    """Write spans + recompile events + metrics (+ optional roofline /
    capacity reports) as JSON-lines; returns `path`.  Defaults to the
    process-wide recorder/log/registry."""
    spans = _spans.recorder().spans() if spans is None else spans
    recompiles = (_recompile.recompile_log().events()
                  if recompiles is None else recompiles)
    registry = _metrics.registry() if registry is None else registry
    # default=str: span attrs / event attrs are arbitrary user kwargs
    # (ndarrays, dtypes, ...) — one odd attr must not abort the dump
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({
            "kind": "meta", "version": 1,
            "capture_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                         time.gmtime()),
        }) + "\n")
        for s in spans:
            fh.write(json.dumps({"kind": "span", **s.to_dict()},
                                default=str) + "\n")
        for e in recompiles:
            # the event dict has its own "kind" (jit | serving-aot), so
            # it nests under "event" instead of colliding with the
            # record discriminator
            fh.write(json.dumps({"kind": "recompile",
                                 "event": e.to_dict()},
                                default=str) + "\n")
        for m in registry.collect():
            rec = {"kind": "metric", "name": m.name, "type": m.kind,
                   "labels": m.labels}
            rec["value"] = (m.summary() if m.kind == "histogram"
                            else m.value)
            fh.write(json.dumps(rec, default=str) + "\n")
        for rep in rooflines or ():
            d = rep if isinstance(rep, dict) else rep.to_dict()
            fh.write(json.dumps({"kind": "roofline", "report": d},
                                default=str) + "\n")
        for rep in capacities or ():
            d = rep if isinstance(rep, dict) else rep.to_dict()
            fh.write(json.dumps({"kind": "capacity", "report": d},
                                default=str) + "\n")
    return path


def load_jsonl(path):
    """Parse a :func:`dump_jsonl` file back into plain dict lists:
    ``{"meta": dict|None, "spans": [...], "recompiles": [...],
    "metrics": [...], "rooflines": [...], "capacities": [...]}``."""
    out = {"meta": None, "spans": [], "recompiles": [], "metrics": [],
           "rooflines": [], "capacities": []}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.pop("kind", None)
            if kind == "meta":
                out["meta"] = rec
            elif kind == "span":
                out["spans"].append(rec)
            elif kind == "recompile":
                # loaded entries match live RecompileEvent.to_dict()
                # shape (their "kind" is jit | serving-aot)
                out["recompiles"].append(rec.get("event", rec))
            elif kind == "metric":
                out["metrics"].append(rec)
            elif kind == "roofline":
                out["rooflines"].append(rec.get("report", rec))
            elif kind == "capacity":
                out["capacities"].append(rec.get("report", rec))
    return out


# ------------------------------------------------------------- Prometheus
def _escape_label(v):
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_labels(labels, extra=None):
    items = sorted((labels or {}).items())
    if extra:
        items = items + list(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in items) \
        + "}"


def _fmt_value(v):
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry=None):
    """The registry in Prometheus text exposition format.

    Counters keep their registered name (callers choose `_total`
    suffixes), histograms render as `summary` quantiles over the
    bounded reservoir plus exact `_sum` / `_count`."""
    registry = _metrics.registry() if registry is None else registry
    lines = []
    seen_header = set()
    for m in registry.collect():
        if m.name not in seen_header:
            seen_header.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            kind = "summary" if m.kind == "histogram" else m.kind
            lines.append(f"# TYPE {m.name} {kind}")
        if m.kind == "histogram":
            qs = (0.5, 0.9, 0.99)
            for q, v in zip(qs, m.quantiles(qs)):
                lines.append(
                    f"{m.name}{_fmt_labels(m.labels, [('quantile', q)])} "
                    f"{_fmt_value(v)}")
            lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.sum)}")
            lines.append(f"{m.name}_count{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.count)}")
        else:
            lines.append(f"{m.name}{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


# ----------------------------------------------------------- Chrome trace
def chrome_trace(spans=None, recompiles=None):
    """Span buffer + compile events as a Chrome/Perfetto
    ``traceEvents`` document.

    Recompile events become global instant markers (``ph: "i"``) at
    their monotonic timestamp — the same clock base the span records
    use — so a mid-run retrace is VISIBLE at the step where it
    happened instead of only counted in the log.  Events from an old
    dump that predates ``t_ns`` are skipped (no clock to place them
    on).

    With an explicit `spans` list (a loaded dump), `recompiles`
    defaults EMPTY rather than to the live log — another process's
    perf_counter epoch has no relation to this one's, so mixing them
    would scatter markers at meaningless timestamps."""
    if spans is None:
        spans = _spans.recorder().spans()
        if recompiles is None:
            recompiles = _recompile.recompile_log().events()
    recompiles = recompiles if recompiles is not None else ()
    tids = {}
    events = []
    for s in spans:
        d = s.to_dict() if isinstance(s, _spans.SpanRecord) else dict(s)
        tid = tids.setdefault(d["thread_id"], len(tids))
        ev = {
            "name": d["name"], "ph": "X", "pid": 0, "tid": tid,
            "ts": d["start_ns"] / 1e3,      # us
            "dur": d["dur_ns"] / 1e3,
        }
        args = dict(d.get("attrs") or {})
        if d.get("trace") is not None:
            # distributed-trace identity (fleettrace): clickable in
            # Perfetto's args pane next to the span's own attrs
            args["trace"] = d["trace"]
            args["span"] = d.get("span")
            if d.get("parent") is not None:
                args["parent"] = d["parent"]
        if args:
            ev["args"] = args
        events.append(ev)
    for e in recompiles:
        d = e.to_dict() if isinstance(e, _recompile.RecompileEvent) \
            else dict(e)
        if d.get("t_ns") is None:
            continue
        args = {"cause": d.get("cause", ""), "seq": d.get("seq")}
        for c in d.get("changes", ()) or ():
            args[c.get("arg", "?")] = (f"{c.get('kind')} "
                                       f"{c.get('before')} -> "
                                       f"{c.get('after')}")
        events.append({
            "name": f"recompile {d.get('fn', '?')} [{d.get('kind')}]",
            "ph": "i", "s": "g", "pid": 0, "tid": 0,
            "ts": d["t_ns"] / 1e3,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, spans=None, recompiles=None):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(chrome_trace(spans, recompiles), fh, default=str)
    return path


# -------------------------------------------------------- scrape endpoint
class _ScrapeHandler(http.server.BaseHTTPRequestHandler):
    """GET /metrics (or /) -> live Prometheus text exposition."""

    registry = None             # bound by serve_prometheus per server

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path.split("?", 1)[0] not in ("/", "/metrics"):
            self.send_error(404, "scrape at /metrics")
            return
        body = prometheus_text(self.registry).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 — http.server API
        pass                    # scrapes must not spam stderr


class PrometheusServer:
    """Owned handle for one live scrape endpoint.

    The serving thread is a daemon AND joined by :meth:`shutdown`
    (idempotent; also a context manager) — the RL105 lifecycle
    contract: the process can always exit, and an owner that shuts
    down gets a fully-stopped server back, not a leak."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self._closed = False

    @property
    def port(self):
        return self._server.server_address[1]

    @property
    def url(self):
        host = self._server.server_address[0]
        return f"http://{host}:{self.port}/metrics"

    def shutdown(self, timeout=5.0):
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._thread.join(timeout)
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown()
        return False


def serve_prometheus(port=0, addr="127.0.0.1", registry=None):
    """Serve the live registry at ``http://{addr}:{port}/metrics`` from
    a daemon thread; ``port=0`` picks a free port.  Returns a
    :class:`PrometheusServer` (read ``.port`` / ``.url``, call
    ``.shutdown()``).  This is the scrape surface ROADMAP item 3's
    multi-engine router reads TTFT / ITL / queue-depth /
    page-occupancy from."""
    handler = type("_BoundScrapeHandler", (_ScrapeHandler,),
                   {"registry": registry})
    server = http.server.ThreadingHTTPServer((addr, int(port)), handler)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever,
                              name="obs-prometheus-scrape", daemon=True)
    thread.start()
    return PrometheusServer(server, thread)
