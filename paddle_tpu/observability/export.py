"""Exporters: JSONL dump, Prometheus text exposition, Chrome trace.

Three render targets for the same in-process state (span ring buffer,
metrics registry, recompile log):

- :func:`dump_jsonl` / :func:`load_jsonl` — one self-describing line
  per record (``{"kind": "span" | "recompile" | "metric" | "meta"}``),
  the interchange format ``tools/obs_report.py`` reads;
- :func:`prometheus_text` — the text exposition format (counters,
  gauges, and reservoir histograms as Prometheus `summary` quantiles)
  a scrape endpoint or node textfile collector can serve as-is;
- :func:`chrome_trace` / :func:`write_chrome_trace` — the span buffer
  as Chrome ``traceEvents`` JSON, loadable in Perfetto / chrome://tracing.
"""
from __future__ import annotations

import json
import time

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import recompile as _recompile
from paddle_tpu.observability import spans as _spans

__all__ = [
    "dump_jsonl", "load_jsonl", "prometheus_text", "chrome_trace",
    "write_chrome_trace",
]


# ------------------------------------------------------------------ JSONL
def dump_jsonl(path, spans=None, recompiles=None, registry=None):
    """Write spans + recompile events + metrics as JSON-lines; returns
    `path`.  Defaults to the process-wide recorder/log/registry."""
    spans = _spans.recorder().spans() if spans is None else spans
    recompiles = (_recompile.recompile_log().events()
                  if recompiles is None else recompiles)
    registry = _metrics.registry() if registry is None else registry
    # default=str: span attrs / event attrs are arbitrary user kwargs
    # (ndarrays, dtypes, ...) — one odd attr must not abort the dump
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({
            "kind": "meta", "version": 1,
            "capture_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                         time.gmtime()),
        }) + "\n")
        for s in spans:
            fh.write(json.dumps({"kind": "span", **s.to_dict()},
                                default=str) + "\n")
        for e in recompiles:
            # the event dict has its own "kind" (jit | serving-aot), so
            # it nests under "event" instead of colliding with the
            # record discriminator
            fh.write(json.dumps({"kind": "recompile",
                                 "event": e.to_dict()},
                                default=str) + "\n")
        for m in registry.collect():
            rec = {"kind": "metric", "name": m.name, "type": m.kind,
                   "labels": m.labels}
            rec["value"] = (m.summary() if m.kind == "histogram"
                            else m.value)
            fh.write(json.dumps(rec, default=str) + "\n")
    return path


def load_jsonl(path):
    """Parse a :func:`dump_jsonl` file back into plain dict lists:
    ``{"meta": dict|None, "spans": [...], "recompiles": [...],
    "metrics": [...]}``."""
    out = {"meta": None, "spans": [], "recompiles": [], "metrics": []}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            kind = rec.pop("kind", None)
            if kind == "meta":
                out["meta"] = rec
            elif kind == "span":
                out["spans"].append(rec)
            elif kind == "recompile":
                # loaded entries match live RecompileEvent.to_dict()
                # shape (their "kind" is jit | serving-aot)
                out["recompiles"].append(rec.get("event", rec))
            elif kind == "metric":
                out["metrics"].append(rec)
    return out


# ------------------------------------------------------------- Prometheus
def _escape_label(v):
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _fmt_labels(labels, extra=None):
    items = sorted((labels or {}).items())
    if extra:
        items = items + list(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in items) \
        + "}"


def _fmt_value(v):
    if v is None:
        return "NaN"
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry=None):
    """The registry in Prometheus text exposition format.

    Counters keep their registered name (callers choose `_total`
    suffixes), histograms render as `summary` quantiles over the
    bounded reservoir plus exact `_sum` / `_count`."""
    registry = _metrics.registry() if registry is None else registry
    lines = []
    seen_header = set()
    for m in registry.collect():
        if m.name not in seen_header:
            seen_header.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            kind = "summary" if m.kind == "histogram" else m.kind
            lines.append(f"# TYPE {m.name} {kind}")
        if m.kind == "histogram":
            qs = (0.5, 0.9, 0.99)
            for q, v in zip(qs, m.quantiles(qs)):
                lines.append(
                    f"{m.name}{_fmt_labels(m.labels, [('quantile', q)])} "
                    f"{_fmt_value(v)}")
            lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.sum)}")
            lines.append(f"{m.name}_count{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.count)}")
        else:
            lines.append(f"{m.name}{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


# ----------------------------------------------------------- Chrome trace
def chrome_trace(spans=None):
    """Span buffer as a Chrome/Perfetto ``traceEvents`` document."""
    spans = _spans.recorder().spans() if spans is None else spans
    tids = {}
    events = []
    for s in spans:
        d = s.to_dict() if isinstance(s, _spans.SpanRecord) else dict(s)
        tid = tids.setdefault(d["thread_id"], len(tids))
        ev = {
            "name": d["name"], "ph": "X", "pid": 0, "tid": tid,
            "ts": d["start_ns"] / 1e3,      # us
            "dur": d["dur_ns"] / 1e3,
        }
        if d.get("attrs"):
            ev["args"] = d["attrs"]
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path, spans=None):
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(chrome_trace(spans), fh, default=str)
    return path
