"""paddle_tpu.observability — unified runtime telemetry.

One observability layer the way ``paddle_tpu.analysis`` is one static-
analysis layer, replacing three disconnected metric silos (serving
engine counters, the profiler's metrics-source registry, ad-hoc bench
lanes) with four pieces:

- :mod:`spans` — nested trace spans (``with span("train_step"): ...``)
  that always record into a bounded ring buffer and additionally emit
  ``jax.profiler.TraceAnnotation`` while a capture is active;
- :mod:`metrics` — ONE process-wide registry of Counter / Gauge /
  Histogram; ``profiler.register_metrics_source`` / ``metrics_report``
  and ``serving.metrics`` are compatibility shims over it;
- :mod:`recompile` — the compile-event log: every
  ``StaticFunction`` cache miss and every serving AOT compile records
  WHY it compiled (which argument's shape / dtype / static leaf
  changed) plus wall-clock trace+compile time;
- :mod:`export` — JSONL, Prometheus text exposition (plus a live
  scrape endpoint, :func:`export.serve_prometheus`), and Chrome-trace
  exporters; rendered by the ``tools/obs_report.py`` CLI;
- :mod:`fleettrace` — the cross-PROCESS layer: trace-context
  propagation (:class:`TraceContext` / :class:`use_context`), durable
  per-rank telemetry spools with a coordination-KV clock handshake,
  the fleet aggregator (``merge_spools`` → one Chrome trace, merged
  metrics, per-request TTFT timelines via ``obs_report --fleet``),
  and the crash flight recorder;
- :mod:`profile` — the whole-program roofline profiler: deterministic
  per-op flops/bytes attributed back to model layers through
  ``jax.named_scope`` threading, classified compute- vs memory-bound
  against chip specs, reconciled with span wall-times and XLA
  ``cost_analysis()`` totals; regression-gated by ``tools/perfgate.py``.

Quickstart::

    from paddle_tpu import observability as obs

    with obs.span("train_step", step=i):
        loss = train_step(x, y)

    obs.recompile_log().events()       # why did anything recompile?
    obs.registry().snapshot()          # every counter/gauge/histogram
    obs.export.dump_jsonl("obs.jsonl")  # -> tools/obs_report.py obs.jsonl

See docs/observability.md for the architecture.
"""
from paddle_tpu.observability import export
from paddle_tpu.observability import fleettrace
from paddle_tpu.observability import profile
from paddle_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry, registry)
from paddle_tpu.observability.profile import (ChipSpec, LayerCost,
                                              RooflineReport,
                                              profile_engine,
                                              profile_static_function,
                                              profile_traced, reconcile)
from paddle_tpu.observability.recompile import (RecompileEvent,
                                                RecompileLog,
                                                note_aot_compile,
                                                note_jit_compile,
                                                recompile_log)
from paddle_tpu.observability.spans import (SpanRecord, SpanRecorder,
                                            TraceContext,
                                            current_context, enabled,
                                            recorder, set_enabled,
                                            span, use_context)

__all__ = [
    "ChipSpec",
    "Counter",
    "Gauge",
    "Histogram",
    "LayerCost",
    "MetricsRegistry",
    "RecompileEvent",
    "RecompileLog",
    "RooflineReport",
    "SpanRecord",
    "SpanRecorder",
    "TraceContext",
    "current_context",
    "enabled",
    "export",
    "fleettrace",
    "note_aot_compile",
    "note_jit_compile",
    "profile",
    "profile_engine",
    "profile_static_function",
    "profile_traced",
    "reconcile",
    "recompile_log",
    "recorder",
    "registry",
    "set_enabled",
    "span",
    "use_context",
]

# built-in metrics sources: the span aggregates and the recompile log
# surface in every profiler.metrics_report() without extra wiring
registry().register_source(
    "spans", lambda: {"dropped": recorder().dropped,
                      "buffered": len(recorder().spans()),
                      "by_name": recorder().aggregates()},
    builtin=True)
registry().register_source(
    "recompile", lambda: recompile_log().snapshot(), builtin=True)
