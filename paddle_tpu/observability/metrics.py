"""One process-wide metrics registry: Counter / Gauge / Histogram.

This is the single backing store every metric in the repo flows
through.  `paddle_tpu.serving.metrics.Histogram` is an alias of the
Histogram here, `profiler.register_metrics_source` / `metrics_report`
are compatibility shims over :meth:`MetricsRegistry.register_source` /
:meth:`MetricsRegistry.report`, and the Prometheus/JSONL exporters in
:mod:`paddle_tpu.observability.export` render :meth:`collect` — so a
counter bumped by the serving engine, a span aggregate, and a recompile
event all land in the same report instead of three disconnected silos.

Instruments are keyed by ``(name, labels)``: asking for an existing
pair returns the SAME instrument (Prometheus semantics), asking for the
same name with a different kind raises.  Everything here is pure
Python; the hot-path cost of an observation is one deque append.
"""
from __future__ import annotations

import itertools
import threading
from collections import deque

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
]


def _label_key(labels):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    __slots__ = ("name", "help", "labels", "_lock")
    kind = "untyped"

    def __init__(self, name, help="", labels=None):
        self.name = str(name)
        self.help = help
        self.labels = dict(labels or {})
        # Mutation is guarded per instrument: this registry is THE
        # process-wide store and observations arrive from any thread
        # (spans record thread_id; engines/steppers run off-thread), so
        # += on shared state must not lose updates at GIL preemption.
        self._lock = threading.Lock()


class Counter(_Instrument):
    """Monotonically increasing count."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError("Counter can only increase")
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge(_Instrument):
    """A value that can go up and down (queue depth, pages in use)."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v):
        self._value = float(v)

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram(_Instrument):
    """Bounded-memory reservoir histogram: keeps the most recent `cap`
    observations (seconds) and summarizes on demand.  `observe` is in
    per-token hot paths, so eviction must be O(1) (deque maxlen).

    The ``summary()`` contract (``{count, mean, p50, p99}`` scaled,
    default seconds -> ms) is the one `serving.metrics` shipped with;
    that module now aliases this class.
    """

    __slots__ = ("cap", "_vals", "count", "sum")
    kind = "histogram"

    def __init__(self, cap=4096, name="", help="", labels=None):
        super().__init__(name, help, labels)
        self.cap = int(cap)
        self._vals = deque(maxlen=self.cap)
        self.count = 0
        self.sum = 0.0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self._vals.append(v)

    def _sorted_vals(self):
        # copy under the lock: sorting/iterating the live deque races
        # with a concurrent observe() (deque mutation during iteration)
        with self._lock:
            return sorted(self._vals)

    @staticmethod
    def _at(vs, q):
        idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
        return vs[idx]

    def quantiles(self, qs):
        """Reservoir values at each q in `qs` from ONE sort (a scrape
        asking for p50/p90/p99 must not re-sort per quantile); None per
        entry when empty."""
        vs = self._sorted_vals()
        if not vs:
            return [None] * len(qs)
        return [self._at(vs, q) for q in qs]

    def percentile(self, q):
        return self.quantiles((q,))[0]

    def summary(self, scale=1000.0):
        """{count, mean, p50, p99} — scaled (default: seconds -> ms)."""
        vs = self._sorted_vals()
        if not vs:
            return {"count": self.count, "mean": None, "p50": None,
                    "p99": None}
        return {
            "count": self.count,
            "mean": round(sum(vs) / len(vs) * scale, 4),
            "p50": round(self._at(vs, 0.50) * scale, 4),
            "p99": round(self._at(vs, 0.99) * scale, 4),
        }


class MetricsRegistry:
    """Process-wide instrument table + named snapshot sources.

    Sources are the coarse integration surface long-running subsystems
    (the serving engine, the dataloader pools) already used through
    `profiler.register_metrics_source`: a zero-arg callable returning a
    plain dict.  :meth:`report` collects every source PLUS the
    registry's own instruments under the reserved ``"observability"``
    key, so one call still sees everything.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}          # (name, label_key) -> instrument
        self._kinds = {}            # name -> kind (conflict detection)
        self._sources = {}          # name -> zero-arg callable
        self._builtins = {}         # subset of _sources surviving reset()

    # ------------------------------------------------- instruments
    def _get_or_create(self, cls, name, help, labels, **kw):
        key = (str(name), _label_key(labels))
        with self._lock:
            inst = self._metrics.get(key)
            if inst is not None:
                if inst.kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                return inst
            if self._kinds.get(key[0], cls.kind) != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{self._kinds[key[0]]}, not {cls.kind}")
            inst = cls(name=name, help=help, labels=labels, **kw)
            self._metrics[key] = inst
            self._kinds[key[0]] = cls.kind
            return inst

    def counter(self, name, help="", labels=None):
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None):
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=None, cap=4096):
        return self._get_or_create(Histogram, name, help, labels, cap=cap)

    def collect(self):
        """All instruments, deterministically ordered (name, labels)."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def drop_labeled(self, labels):
        """Remove every instrument whose labels include all of `labels`
        (a finite-lifetime owner — e.g. one serving engine — releasing
        its instruments so the registry does not grow with dead owners).
        Returns the number of instruments dropped."""
        want = set(_label_key(labels))
        if not want:
            raise ValueError("drop_labeled needs at least one label")
        with self._lock:
            victims = [k for k in self._metrics if want <= set(k[1])]
            for k in victims:
                del self._metrics[k]
            for name in {k[0] for k in victims}:
                if not any(k[0] == name for k in self._metrics):
                    self._kinds.pop(name, None)
            return len(victims)

    def snapshot(self):
        """Plain-dict view: ``name{k=v,...}`` -> value / summary."""
        out = {}
        for m in self.collect():
            label = "" if not m.labels else "{" + ",".join(
                f"{k}={v}" for k, v in sorted(m.labels.items())) + "}"
            out[m.name + label] = (m.summary() if m.kind == "histogram"
                                   else m.value)
        return out

    # ---------------------------------------------------- sources
    def register_source(self, name, snapshot_fn, builtin=False):
        """Register `snapshot_fn` (zero-arg -> dict) under `name`.
        Re-registering a name replaces the previous source.  A
        ``builtin`` source (the package-level span/recompile views,
        registered once at import) survives :meth:`reset`."""
        if not callable(snapshot_fn):
            raise TypeError("snapshot_fn must be callable")
        with self._lock:
            self._sources[name] = snapshot_fn
            if builtin:
                self._builtins[name] = snapshot_fn
        return name

    def unregister_source(self, name, expected=None):
        """Remove the source under `name`.  With `expected`, remove it
        only if the registered callable is that exact object — an owner
        whose name was since re-registered by a newer owner (rolling
        restart with a stable name) must not tear down the successor."""
        with self._lock:
            if (expected is not None
                    and self._sources.get(name) is not expected):
                return
            self._sources.pop(name, None)

    def report(self):
        """{source_name: snapshot_dict} for every registered source,
        plus the registry's own instruments under ``"observability"``;
        a source that raises reports {"error": ...} instead of killing
        the whole report."""
        with self._lock:
            sources = list(self._sources.items())
        out = {}
        for name, fn in sources:
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — must not throw
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        out["observability"] = {"metrics": self.snapshot()}
        return out

    def reset(self):
        """Drop every instrument and non-builtin source (test
        isolation).  Builtin sources are re-installed because the
        package import that registered them runs only once per
        process — dropping them here would silently remove the span /
        recompile views from every later report."""
        with self._lock:
            self._metrics.clear()
            self._kinds.clear()
            self._sources = dict(self._builtins)


_REGISTRY = MetricsRegistry()

# unique default label values for unnamed per-instance metric owners
# (e.g. a bare EngineMetrics() in a test): never reuse another
# instance's instruments by accident
_instance_seq = itertools.count()


def next_instance_label(prefix):
    return f"{prefix}{next(_instance_seq)}"


def registry():
    """THE process-wide registry (module singleton)."""
    return _REGISTRY
