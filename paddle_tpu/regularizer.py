"""Weight-decay regularizers. Reference: python/paddle/regularizer.py."""
from __future__ import annotations


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
