"""paddle.geometric.message_passing utils parity (reference:
geometric/message_passing/utils.py:22,36,61)."""
from __future__ import annotations

import numpy as np

__all__ = ["convert_out_size_to_list", "get_out_size_tensor_inputs",
           "reshape_lhs_rhs"]


def convert_out_size_to_list(out_size):
    """Normalize out_size (None | int | 0-d tensor) to a 1-list."""
    if out_size is None:
        return [0]
    if isinstance(out_size, (int, np.integer)):
        return [int(out_size)]
    return [int(np.asarray(out_size.numpy()
                           if hasattr(out_size, "numpy")
                           else out_size).reshape(-1)[0])]


def get_out_size_tensor_inputs(inputs, attrs, out_size, op_type):
    """Static-graph form: record out_size into attrs/inputs. Shapes are
    static under XLA, so a tensor out_size is materialized at trace
    time."""
    if out_size is None:
        attrs["out_size"] = [0]
    elif isinstance(out_size, (int, np.integer)):
        attrs["out_size"] = [int(out_size)]
    else:
        inputs["Out_size"] = out_size
    return inputs, attrs


def reshape_lhs_rhs(x, y):
    """Pad the lower-rank operand with middle singleton dims so
    elementwise message ops broadcast like the reference."""
    import paddle_tpu as P
    if len(x.shape) == 1:
        x = P.reshape(x, [-1, 1])
    if len(y.shape) == 1:
        y = P.reshape(y, [-1, 1])
    if len(x.shape) != len(y.shape):
        max_nd = max(len(x.shape), len(y.shape))
        if len(x.shape) < max_nd:
            shape = [x.shape[0]] + [1] * (max_nd - len(x.shape)) + \
                list(x.shape[1:])
            x = P.reshape(x, shape)
        else:
            shape = [y.shape[0]] + [1] * (max_nd - len(y.shape)) + \
                list(y.shape[1:])
            y = P.reshape(y, shape)
    return x, y
