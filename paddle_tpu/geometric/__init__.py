"""paddle.geometric parity namespace.

Reference: python/paddle/geometric — message_passing/send_recv.py
(send_u_recv :35, send_ue_recv :185, send_uv :387), math.py
(segment_sum/mean/min/max), reindex.py (reindex_graph), sampling/
neighbors.py (sample_neighbors).

TPU-native design: the reference's fused CUDA graph kernels become
jax.ops.segment_* reductions (XLA scatter-reduce — fully differentiable
and jittable with a static out_size); the sampling/reindex utilities are
host-side preprocessing (numpy) exactly like the reference's CPU
kernels, feeding static-shape device programs.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dispatch import apply
from paddle_tpu.core.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_min", "segment_max", "reindex_graph",
           "reindex_heter_graph", "sample_neighbors"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


_SEGMENT = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # sum / count
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _segment_reduce(data, ids, num, op):
    ids = ids.astype(jnp.int32)
    if op == "mean":
        s = jax.ops.segment_sum(data, ids, num)
        cnt = jax.ops.segment_sum(jnp.ones(ids.shape, data.dtype), ids, num)
        return s / jnp.maximum(cnt, 1.0).reshape(
            (-1,) + (1,) * (data.ndim - 1))
    out = _SEGMENT[op](data, ids, num)
    if op in ("min", "max"):
        # empty segments come back +/-inf; the reference zeroes them
        cnt = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.float32), ids,
                                  num)
        empty = (cnt == 0).reshape((-1,) + (1,) * (data.ndim - 1))
        out = jnp.where(empty, 0.0, out).astype(data.dtype)
    return out


def segment_sum(data, segment_ids, name=None):
    num = int(np.asarray(jax.device_get(_v(segment_ids))).max()) + 1 \
        if _v(segment_ids).size else 0
    return apply(lambda d, i: _segment_reduce(d, i, num, "sum"),
                 _t(data), _t(segment_ids))


def segment_mean(data, segment_ids, name=None):
    num = int(np.asarray(jax.device_get(_v(segment_ids))).max()) + 1 \
        if _v(segment_ids).size else 0
    return apply(lambda d, i: _segment_reduce(d, i, num, "mean"),
                 _t(data), _t(segment_ids))


def segment_min(data, segment_ids, name=None):
    num = int(np.asarray(jax.device_get(_v(segment_ids))).max()) + 1 \
        if _v(segment_ids).size else 0
    return apply(lambda d, i: _segment_reduce(d, i, num, "min"),
                 _t(data), _t(segment_ids))


def segment_max(data, segment_ids, name=None):
    num = int(np.asarray(jax.device_get(_v(segment_ids))).max()) + 1 \
        if _v(segment_ids).size else 0
    return apply(lambda d, i: _segment_reduce(d, i, num, "max"),
                 _t(data), _t(segment_ids))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and scatter-reduce onto dst: one message-passing
    step. out_size defaults to x.shape[0] (reference: max(dst)+1 padded
    to input size)."""
    n = int(out_size) if out_size is not None else _v(x).shape[0]

    def fn(xv, si, di):
        msgs = xv[si.astype(jnp.int32)]
        return _segment_reduce(msgs, di, n, reduce_op)

    return apply(fn, _t(x), _t(src_index), _t(dst_index))


_MSG = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Message = x[src] (message_op) y[edge]; scatter-reduced onto dst."""
    n = int(out_size) if out_size is not None else _v(x).shape[0]
    mop = _MSG[message_op]

    def fn(xv, yv, si, di):
        msgs = mop(xv[si.astype(jnp.int32)], yv)
        return _segment_reduce(msgs, di, n, reduce_op)

    return apply(fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst] — no reduction."""
    mop = _MSG[message_op]

    def fn(xv, yv, si, di):
        return mop(xv[si.astype(jnp.int32)], yv[di.astype(jnp.int32)])

    return apply(fn, _t(x), _t(y), _t(src_index), _t(dst_index))


def _reindex(xs, nb):
    """Dense-reindex helper: input nodes first, new neighbor nodes
    appended in first-seen order. Returns (src_indices, out_nodes)."""
    mapping = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    src = np.empty(len(nb), np.int64)
    for i, v in enumerate(nb):
        vi = int(v)
        if vi not in mapping:
            mapping[vi] = len(out_nodes)
            out_nodes.append(vi)
        src[i] = mapping[vi]
    return src, np.asarray(out_nodes, xs.dtype)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex sampled subgraph ids to a dense [0, n) range; input nodes
    first, new neighbor nodes appended in first-seen order."""
    xs = np.asarray(jax.device_get(_v(x)))
    nb = np.asarray(jax.device_get(_v(neighbors)))
    cnt = np.asarray(jax.device_get(_v(count)))
    src, out_nodes = _reindex(xs, nb)
    dst = np.repeat(np.arange(len(xs)), cnt)
    dt = xs.dtype
    return (Tensor(jnp.asarray(src.astype(dt))),
            Tensor(jnp.asarray(dst.astype(dt))),
            Tensor(jnp.asarray(out_nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reindex_graph over per-edge-type neighbor/count lists sharing one
    node mapping (reference reindex.py reindex_heter_graph)."""
    xs = np.asarray(jax.device_get(_v(x)))
    nbs = [np.asarray(jax.device_get(_v(n))) for n in neighbors]
    cnts = [np.asarray(jax.device_get(_v(c))) for c in count]
    merged = np.concatenate(nbs) if nbs else np.zeros(0, xs.dtype)
    src_all, out_nodes = _reindex(xs, merged)
    offs = np.cumsum([0] + [len(n) for n in nbs])
    dt = xs.dtype
    srcs = [Tensor(jnp.asarray(src_all[offs[i]:offs[i + 1]].astype(dt)))
            for i in range(len(nbs))]
    dsts = [Tensor(jnp.asarray(
        np.repeat(np.arange(len(xs)), c).astype(dt))) for c in cnts]
    return srcs, dsts, Tensor(jnp.asarray(out_nodes))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniformly sample up to sample_size neighbors per input node from a
    CSC graph (host-side, like the reference CPU kernel). Returns
    (out_neighbors, out_count[, out_eids])."""
    rw = np.asarray(jax.device_get(_v(row))).reshape(-1)
    cp = np.asarray(jax.device_get(_v(colptr))).reshape(-1)
    nodes = np.asarray(jax.device_get(_v(input_nodes))).reshape(-1)
    if return_eids and eids is None:
        raise ValueError("return_eids=True requires eids")
    ev = np.asarray(jax.device_get(_v(eids))).reshape(-1) \
        if eids is not None else None
    rng = np.random.default_rng()
    out_n, out_c, out_e = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            pick = np.arange(beg, end)
        else:
            pick = beg + rng.choice(deg, size=sample_size, replace=False)
        out_n.append(rw[pick])
        out_c.append(len(pick))
        if ev is not None:
            out_e.append(ev[pick])
    neigh = np.concatenate(out_n) if out_n else np.zeros(0, rw.dtype)
    cnt = np.asarray(out_c, np.int32)
    res = (Tensor(jnp.asarray(neigh)), Tensor(jnp.asarray(cnt)))
    if return_eids:
        e = np.concatenate(out_e) if out_e else np.zeros(0, rw.dtype)
        res = res + (Tensor(jnp.asarray(e)),)
    return res
from paddle_tpu.geometric import message_passing  # noqa: E402,F401
