"""Serialized inference programs via jax.export (StableHLO).

Reference parity: python/paddle/jit/api.py jit.save/jit.load +
static.save/load_inference_model (ProgramDesc + params on disk; the
AnalysisPredictor reloads and runs them without the Python model class).
TPU-native design: the Layer's forward is functionalized (params lifted to
arguments), jit-traced ONCE per input signature, and exported as versioned
StableHLO bytes — a portable compiled-program artifact that reloads and
runs WITHOUT the model's Python code, which is exactly the role
ProgramDesc played. Params ride alongside as a pickle.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

import jax
from jax import export as jax_export

from paddle_tpu.core.tensor import Tensor

_FORMAT_VERSION = 1


def functional_forward(layer):
    """(params_dict, *arrays) -> tuple of output arrays, via temporary
    param rebinding. Shared by jit serialization and inference.Predictor."""
    def fwd(params_vals, *xs):
        sd = layer.state_dict()
        saved = [(t, t._value) for t in sd.values()]
        try:
            for k, t in sd.items():
                t._value = params_vals[k]
            outs = layer(*[Tensor(x) for x in xs])
            if isinstance(outs, (list, tuple)):
                return tuple(o._value for o in outs)
            return (outs._value,)
        finally:
            for t, v in saved:
                t._value = v
    return fwd


def _specs_to_sds(specs):
    """InputSpec/Tensor/array list -> ShapeDtypeStructs; None/-1 dims become
    jax.export symbolic dims (one shared scope), so the serialized program
    accepts ANY size there — the Paddle 'variable batch' semantics."""
    import jax.numpy as jnp
    from paddle_tpu.core.dtype import convert_dtype
    from paddle_tpu.static import InputSpec

    scope = jax_export.SymbolicScope()
    counter = [0]

    def dim(s):
        if s is None or (isinstance(s, int) and s < 0):
            name = f"d{counter[0]}"
            counter[0] += 1
            return jax_export.symbolic_shape(name, scope=scope)[0]
        return int(s)

    out = []
    for spec in specs:
        if isinstance(spec, InputSpec):
            shape = tuple(dim(s) for s in spec.shape)
            out.append(jax.ShapeDtypeStruct(
                shape, convert_dtype(spec.dtype) or jnp.float32))
        elif isinstance(spec, Tensor):
            out.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                            spec._value.dtype))
        else:
            arr = np.asarray(spec)
            out.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
    return out


def save_program(layer, path, input_spec):
    """Export layer.forward(input_spec...) as StableHLO + params.

    Writes path.pdmodel (serialized exported program + meta) and
    path.pdiparams (params pickle)."""
    was_training = getattr(layer, "training", False)
    layer.eval()
    try:
        sd = layer.state_dict()
        params = {k: t._value for k, t in sd.items()}
        fwd = functional_forward(layer)

        param_sds = {k: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                     for k, v in params.items()}
        in_sds = _specs_to_sds(input_spec)
        exported = jax_export.export(jax.jit(fwd))(param_sds, *in_sds)
        blob = exported.serialize()
    finally:
        if was_training:
            layer.train()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({"version": _FORMAT_VERSION, "stablehlo": blob,
                     "class": type(layer).__name__,
                     "n_inputs": len(in_sds)}, f)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({k: np.asarray(v) for k, v in params.items()}, f)


class TranslatedLayer:
    """A reloaded serialized program: callable like the original Layer's
    forward, with NO dependence on the original Python class (reference:
    paddle.jit.TranslatedLayer)."""

    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self._meta = meta

    def __call__(self, *args):
        import jax.numpy as jnp
        arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        outs = self._exported.call(self._params, *arrs)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else list(outs)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference program")

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._params.items()}


def load_program(path, params_path=None):
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    if meta.get("version") != _FORMAT_VERSION:
        raise ValueError(f"unsupported program version {meta.get('version')}")
    with open(params_path or path + ".pdiparams", "rb") as f:
        import jax.numpy as jnp
        params = {k: jnp.asarray(v) for k, v in pickle.load(f).items()}
    exported = jax_export.deserialize(meta["stablehlo"])
    return TranslatedLayer(exported, params, meta)
