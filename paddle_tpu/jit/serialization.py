"""Serialized inference programs via jax.export (StableHLO).

Reference parity: python/paddle/jit/api.py jit.save/jit.load +
static.save/load_inference_model (ProgramDesc + params on disk; the
AnalysisPredictor reloads and runs them without the Python model class).
TPU-native design: the Layer's forward is functionalized (params lifted to
arguments), jit-traced ONCE per input signature, and exported as versioned
StableHLO bytes — a portable compiled-program artifact that reloads and
runs WITHOUT the model's Python code, which is exactly the role
ProgramDesc played.

Artifact format (deliberately NON-executable — loading never unpickles,
so a downloaded model file cannot run code, unlike pickle):
  <path>.pdmodel   = b"PTPU" + u32 header_len + JSON header + StableHLO bytes
  <path>.pdiparams = .npz archive (np.savez, allow_pickle=False on load);
                     extension dtypes (bfloat16) ride as uint16 with the
                     true dtype recorded in the npz's __dtypes__ JSON entry.
"""
from __future__ import annotations

import json
import os
import struct

import numpy as np

import jax
from jax import export as jax_export

from paddle_tpu.core.tensor import Tensor

_FORMAT_VERSION = 2
_MAGIC = b"PTPU"
_DTYPES_KEY = "__dtypes__"


# ---------------------------------------------------------------- containers
def write_model_file(path, header: dict, blob: bytes = b"") -> None:
    """Write the .pdmodel container: magic + JSON header + raw program."""
    header = dict(header)
    header["version"] = _FORMAT_VERSION
    hdr = json.dumps(header).encode("utf-8")
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(hdr)))
        f.write(hdr)
        f.write(blob)


def read_model_file(path):
    """-> (header dict, program bytes). Rejects legacy/foreign files."""
    with open(path, "rb") as f:
        magic = f.read(4)
        if magic != _MAGIC:
            raise ValueError(
                f"{path}: not a paddle_tpu serialized program (bad magic "
                f"{magic!r}; legacy pickle artifacts are not supported — "
                f"re-save with jit.save)")
        (hdr_len,) = struct.unpack("<I", f.read(4))
        header = json.loads(f.read(hdr_len).decode("utf-8"))
        blob = f.read()
    if header.get("version") != _FORMAT_VERSION:
        raise ValueError(
            f"unsupported program version {header.get('version')}")
    return header, blob


def save_params_npz(path, params) -> None:
    """Save a {name: array} dict as npz. bfloat16 (and any other extension
    dtype numpy can't natively serialize) is stored as a same-width uint
    view, with true dtypes recorded under __dtypes__."""
    arrays = {}
    dtypes = {}
    for k, v in params.items():
        if k == _DTYPES_KEY:
            raise ValueError(f"reserved param name {k!r}")
        a = np.asarray(v)
        dtypes[k] = str(a.dtype)
        if a.dtype.kind not in "biufc" or a.dtype.hasobject:
            a = a.view(np.dtype(f"u{a.dtype.itemsize}"))
        arrays[k] = a
    meta = np.frombuffer(json.dumps(dtypes).encode("utf-8"), dtype=np.uint8)
    with open(path, "wb") as f:
        np.savez(f, **{_DTYPES_KEY: meta}, **arrays)


def load_params_npz(path):
    """Inverse of save_params_npz -> {name: np.ndarray} (true dtypes)."""
    import ml_dtypes

    out = {}
    with np.load(path, allow_pickle=False) as z:
        dtypes = {}
        if _DTYPES_KEY in z.files:
            dtypes = json.loads(bytes(z[_DTYPES_KEY]).decode("utf-8"))
        for k in z.files:
            if k == _DTYPES_KEY:
                continue
            a = z[k]
            want = dtypes.get(k)
            if want and want != str(a.dtype):
                a = a.view(np.dtype(getattr(ml_dtypes, want)))
            out[k] = a
    return out


# ---------------------------------------------------------------- export
def functional_forward(layer):
    """(params_dict, *arrays) -> tuple of output arrays, via temporary
    param rebinding. Shared by jit serialization and inference.Predictor."""
    def fwd(params_vals, *xs):
        sd = layer.state_dict()
        saved = [(t, t._value) for t in sd.values()]
        try:
            for k, t in sd.items():
                t._value = params_vals[k]
            outs = layer(*[Tensor(x) for x in xs])
            if isinstance(outs, (list, tuple)):
                return tuple(o._value for o in outs)
            return (outs._value,)
        finally:
            for t, v in saved:
                t._value = v
    return fwd


def _specs_to_sds(specs):
    """InputSpec/Tensor/array list -> ShapeDtypeStructs; None/-1 dims become
    jax.export symbolic dims (one shared scope), so the serialized program
    accepts ANY size there — the Paddle 'variable batch' semantics."""
    import jax.numpy as jnp
    from paddle_tpu.core.dtype import convert_dtype
    from paddle_tpu.static import InputSpec

    scope = jax_export.SymbolicScope()
    counter = [0]

    def dim(s):
        if s is None or (isinstance(s, int) and s < 0):
            name = f"d{counter[0]}"
            counter[0] += 1
            return jax_export.symbolic_shape(name, scope=scope)[0]
        return int(s)

    out = []
    names = []
    for i, spec in enumerate(specs):
        if isinstance(spec, InputSpec):
            shape = tuple(dim(s) for s in spec.shape)
            out.append(jax.ShapeDtypeStruct(
                shape, convert_dtype(spec.dtype) or jnp.float32))
            names.append(getattr(spec, "name", None) or f"input_{i}")
        elif isinstance(spec, Tensor):
            out.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                            spec._value.dtype))
            names.append(spec.name or f"input_{i}")
        else:
            arr = np.asarray(spec)
            out.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
            names.append(f"input_{i}")
    return out, names


def save_program(layer, path, input_spec):
    """Export layer.forward(input_spec...) as StableHLO + params.

    Writes path.pdmodel (JSON header + StableHLO bytes) and
    path.pdiparams (npz)."""
    was_training = getattr(layer, "training", False)
    layer.eval()
    try:
        sd = layer.state_dict()
        params = {k: t._value for k, t in sd.items()}
        fwd = functional_forward(layer)

        param_sds = {k: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                     for k, v in params.items()}
        in_sds, in_names = _specs_to_sds(input_spec)
        exported = jax_export.export(jax.jit(fwd))(param_sds, *in_sds)
        n_outputs = len(exported.out_avals)
        blob = exported.serialize()
    finally:
        if was_training:
            layer.train()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    write_model_file(path + ".pdmodel", {
        "stablehlo": True,
        "class": type(layer).__name__,
        "n_inputs": len(in_sds),
        "input_names": in_names,
        "output_names": [f"output_{i}" for i in range(n_outputs)],
    }, blob)
    save_params_npz(path + ".pdiparams", params)


class TranslatedLayer:
    """A reloaded serialized program: callable like the original Layer's
    forward, with NO dependence on the original Python class (reference:
    paddle.jit.TranslatedLayer)."""

    def __init__(self, exported, params, meta):
        self._exported = exported
        self._params = params
        self._meta = meta
        self._call_params = None  # params cast to the program's dtypes

    def _program_params(self):
        """Params cast to the exported program's traced dtypes (cached).
        Lets bf16-on-disk params (convert_to_mixed_precision) run a program
        traced in fp32: the upcast happens once, on device."""
        if self._call_params is None:
            import jax.tree_util as jtu
            args, _ = jtu.tree_unflatten(
                self._exported.in_tree, list(self._exported.in_avals))
            expected = args[0]
            self._call_params = {
                k: (v if v.dtype == expected[k].dtype
                    else v.astype(expected[k].dtype))
                for k, v in self._params.items()}
        return self._call_params

    @property
    def input_names(self):
        n = self._meta.get("n_inputs", 0)
        return self._meta.get("input_names") or [
            f"input_{i}" for i in range(n)]

    @property
    def output_names(self):
        return self._meta.get("output_names") or ["output_0"]

    def __call__(self, *args):
        import jax.numpy as jnp
        arrs = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        outs = self._exported.call(self._program_params(), *arrs)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else list(outs)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is an inference program")

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._params.items()}

    def astype(self, dtype):
        """Cast all floating params to `dtype` (bf16 storage; used by
        inference.convert_to_mixed_precision). The StableHLO program keeps
        its traced dtypes — _program_params() casts back at call time — so
        this halves host memory + host→device transfer, not compute. For a
        bf16 compute program, export under amp.auto_cast."""
        from paddle_tpu.core.dtype import convert_dtype
        dt = convert_dtype(dtype)
        self._params = {
            k: (v.astype(dt) if np.issubdtype(np.asarray(v).dtype,
                                              np.floating) else v)
            for k, v in self._params.items()}
        self._call_params = None
        return self


def load_program(path, params_path=None):
    meta, blob = read_model_file(path + ".pdmodel")
    if not meta.get("stablehlo"):
        raise ValueError(f"{path}.pdmodel holds no serialized program")
    import jax.numpy as jnp
    params = {k: jnp.asarray(v)
              for k, v in load_params_npz(
                  params_path or path + ".pdiparams").items()}
    exported = jax_export.deserialize(blob)
    return TranslatedLayer(exported, params, meta)
