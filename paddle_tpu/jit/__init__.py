"""paddle_tpu.jit. Reference: python/paddle/jit/__init__.py."""
import os
import pickle

from paddle_tpu.jit.api import (  # noqa: F401
    ProgramTranslator,
    StaticFunction,
    enable_to_static,
    not_to_static,
    to_static,
)


def save(layer, path, input_spec=None, **configs):
    """Persist a Layer's parameters + structure info.

    Reference: python/paddle/jit/api.py jit.save (saves ProgramDesc +
    params). TPU-native: parameters/buffers as numpy arrays plus the input
    spec; inference reload compiles the forward fresh with XLA (AOT via
    paddle_tpu.inference)."""
    import numpy as np
    from paddle_tpu.nn.layer.layers import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if isinstance(layer, Layer):
        sd = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    else:
        sd = {}
    meta = {
        "class": type(layer).__name__,
        "input_spec": [getattr(s, "_asdict", lambda: repr(s))() if hasattr(s, "_asdict")
                       else repr(s) for s in (input_spec or [])],
    }
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(sd, f)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        sd = pickle.load(f)
    return sd
