"""paddle_tpu.jit. Reference: python/paddle/jit/__init__.py."""
import os

from paddle_tpu.jit.api import (  # noqa: F401
    ProgramTranslator,
    StaticFunction,
    enable_to_static,
    not_to_static,
    to_static,
)


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer as a portable compiled inference program.

    Reference: python/paddle/jit/api.py jit.save (ProgramDesc + params).
    TPU-native: with input_spec, the forward is functionalized and exported
    as versioned StableHLO (jit/serialization.py) — reloadable and runnable
    WITHOUT the model's Python class, the role ProgramDesc played. Without
    input_spec, falls back to params+meta only (reload needs the class).
    Artifacts are non-executable (JSON + StableHLO + npz): loading never
    unpickles untrusted data."""
    import numpy as np
    from paddle_tpu.jit.serialization import (save_params_npz, save_program,
                                              write_model_file)
    from paddle_tpu.nn.layer.layers import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if input_spec and isinstance(layer, Layer):
        save_program(layer, path, input_spec)
        return
    if isinstance(layer, Layer):
        sd = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    else:
        sd = {}
    save_params_npz(path + ".pdiparams", sd)
    write_model_file(path + ".pdmodel", {
        "stablehlo": False,
        "class": type(layer).__name__,
        "input_spec": [],
    })


def load(path, **configs):
    """Reload a jit.save artifact: a TranslatedLayer (callable compiled
    program) when the .pdmodel holds StableHLO, else the params dict."""
    from paddle_tpu.jit.serialization import (load_params_npz, load_program,
                                              read_model_file)

    meta, _ = read_model_file(path + ".pdmodel")
    if meta.get("stablehlo"):
        return load_program(path)
    return load_params_npz(path + ".pdiparams")


# reference jit namespace extras (python/paddle/jit/__init__.py)
from paddle_tpu.jit.serialization import TranslatedLayer  # noqa: E402,F401
from paddle_tpu.jit import dy2static  # noqa: E402,F401

TracedLayer = TranslatedLayer  # legacy alias: trace-based save/load

_code_level = [0]
_verbosity = [0]


def set_code_level(level=100, also_to_stdout=False):
    """Dy2Static debugging knob (reference jit/dy2static logging): there
    is no source-to-source transform here — to_static traces Python
    directly — so this records the level and, at >0, prints a note."""
    _code_level[0] = level
    if level and also_to_stdout:
        print("paddle_tpu.jit: to_static traces Python directly; there "
              "is no transformed code to dump (level recorded)")


def set_verbosity(level=0, also_to_stdout=False):
    _verbosity[0] = level


def get_verbosity():
    return _verbosity[0]


class FunctionInfo:
    """Descriptor for a to_static-converted function (reference
    jit/dy2static/function_spec.py FunctionInfo role): name + location."""

    def __init__(self, function):
        self.function = function
        self.name = getattr(function, "__name__", repr(function))
        code = getattr(function, "__code__", None)
        self.location = (f"{code.co_filename}:{code.co_firstlineno}"
                         if code else "<builtin>")

    def __repr__(self):
        return f"FunctionInfo({self.name} at {self.location})"


# reference jit exposes these names at the package root
Function = StaticFunction
Layer = TranslatedLayer
