"""paddle_tpu.jit. Reference: python/paddle/jit/__init__.py."""
import os
import pickle

from paddle_tpu.jit.api import (  # noqa: F401
    ProgramTranslator,
    StaticFunction,
    enable_to_static,
    not_to_static,
    to_static,
)


def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer as a portable compiled inference program.

    Reference: python/paddle/jit/api.py jit.save (ProgramDesc + params).
    TPU-native: with input_spec, the forward is functionalized and exported
    as versioned StableHLO (jit/serialization.py) — reloadable and runnable
    WITHOUT the model's Python class, the role ProgramDesc played. Without
    input_spec, falls back to params+meta only (reload needs the class)."""
    import numpy as np
    from paddle_tpu.nn.layer.layers import Layer

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if input_spec and isinstance(layer, Layer):
        from paddle_tpu.jit.serialization import save_program
        save_program(layer, path, input_spec)
        return
    if isinstance(layer, Layer):
        sd = {k: np.asarray(v._value) for k, v in layer.state_dict().items()}
    else:
        sd = {}
    meta = {
        "class": type(layer).__name__,
        "input_spec": [],
    }
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(sd, f)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)


def load(path, **configs):
    """Reload a jit.save artifact: a TranslatedLayer (callable compiled
    program) when the .pdmodel holds StableHLO, else the params dict."""
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    if isinstance(meta, dict) and "stablehlo" in meta:
        from paddle_tpu.jit.serialization import load_program
        return load_program(path)
    with open(path + ".pdiparams", "rb") as f:
        return pickle.load(f)
